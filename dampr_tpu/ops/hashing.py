"""Vectorized 64-bit record hashing (dual uint32 lanes).

Replaces the reference's per-record ``hash(key) % n_partitions`` partitioner
(reference dampr/base.py:6-8 ``Splitter``) with a batched kernel: string keys become a
padded uint8 matrix hashed by a dual-lane FNV-1a scan on device; integer keys go
through a murmur-style finalizer.  Two independent 32-bit lanes (h1, h2) stand in for
a 64-bit hash without requiring global ``jax_enable_x64``:

- partition routing uses ``h1 % P`` (cheap, single lane);
- grouping sorts lexicographically on ``(h1, h2)`` via ``lax.sort(num_keys=2)``;
- host bookkeeping combines lanes into one uint64 (``combine64``).

Collisions on the full 64 bits are detected during sort-based grouping
(ops/segment.py ``sort_and_group`` compares real keys of same-hash neighbors and
repairs boundaries), so hashing here only needs to be uniform, not perfect.

Python-equality nuance: ``1 == 1.0 == True`` group together under the reference's
sort+groupby semantics, so integral floats and bools are canonicalized to int64
before hashing.
"""

import functools

import numpy as np

from .. import settings

_FNV_OFFSET1 = np.uint32(2166136261)
_FNV_OFFSET2 = np.uint32(0x9747B28C)
_FNV_PRIME1 = np.uint32(16777619)
_FNV_PRIME2 = np.uint32(0x85EBCA6B)

# Length padding buckets bound jit recompilations for variable-width string blocks.
_LEN_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)


def _len_bucket(max_len):
    for b in _LEN_BUCKETS:
        if max_len <= b:
            return b
    # Very long keys: round up to a multiple of 1024.
    return ((max_len + 1023) // 1024) * 1024


def _pow2_rows(n):
    p = 1 << max(0, (n - 1).bit_length())
    return max(p, 8)


def encode_str_keys(keys):
    """Encode a sequence of str/bytes keys as (padded uint8 [N, L], lengths int32 [N]).

    UTF-8 encodes str; bytes pass through.  L is bucketed to bound compilations.
    """
    bs = [k.encode("utf-8") if isinstance(k, str) else bytes(k) for k in keys]
    n = len(bs)
    max_len = max((len(b) for b in bs), default=1)
    L = _len_bucket(max(max_len, 1))
    mat = np.zeros((n, L), dtype=np.uint8)
    lens = np.empty(n, dtype=np.int32)
    for i, b in enumerate(bs):
        lens[i] = len(b)
        if b:
            mat[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return mat, lens


# ---------------------------------------------------------------------------
# numpy host path
# ---------------------------------------------------------------------------

def _fnv_numpy(mat, lens):
    n, L = mat.shape
    h1 = np.full(n, _FNV_OFFSET1, dtype=np.uint32)
    h2 = np.full(n, _FNV_OFFSET2, dtype=np.uint32)
    cols = np.arange(L, dtype=np.int32)
    with np.errstate(over="ignore"):
        for c in range(L):
            active = cols[c] < lens
            b = mat[:, c].astype(np.uint32)
            nh1 = (h1 ^ b) * _FNV_PRIME1
            nh2 = (h2 ^ b) * _FNV_PRIME2
            h1 = np.where(active, nh1, h1)
            h2 = np.where(active, nh2, h2)
    return h1, h2


def _mix_int_numpy(vals_i64):
    v = vals_i64.astype(np.uint64)
    lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (v >> np.uint64(32)).astype(np.uint32)
    with np.errstate(over="ignore"):
        h1 = _murmur_fmix_np(lo ^ np.uint32(0x9E3779B9), hi)
        h2 = _murmur_fmix_np(lo ^ np.uint32(0x85EBCA6B), hi ^ np.uint32(0xC2B2AE35))
    return h1, h2


def _murmur_fmix_np(x, y):
    h = x
    h ^= y
    h ^= h >> np.uint32(16)
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h ^= h >> np.uint32(13)
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h ^= h >> np.uint32(16)
    return h


# ---------------------------------------------------------------------------
# JAX device path
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _fnv_jit():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def kernel(mat, lens):
        n, L = mat.shape
        h1 = jnp.full((n,), _FNV_OFFSET1, dtype=jnp.uint32)
        h2 = jnp.full((n,), _FNV_OFFSET2, dtype=jnp.uint32)

        def body(c, hs):
            h1, h2 = hs
            active = c < lens
            b = mat[:, c].astype(jnp.uint32)
            nh1 = (h1 ^ b) * _FNV_PRIME1
            nh2 = (h2 ^ b) * _FNV_PRIME2
            return (jnp.where(active, nh1, h1), jnp.where(active, nh2, h2))

        h1, h2 = lax.fori_loop(0, L, body, (h1, h2))
        return h1, h2

    return jax.jit(kernel)


@functools.lru_cache(maxsize=None)
def _mix_int_jit():
    import jax
    import jax.numpy as jnp

    def fmix(x, y):
        h = x ^ y
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        return h

    def kernel(lo, hi):
        h1 = fmix(lo ^ jnp.uint32(0x9E3779B9), hi)
        h2 = fmix(lo ^ jnp.uint32(0x85EBCA6B), hi ^ jnp.uint32(0xC2B2AE35))
        return h1, h2

    return jax.jit(kernel)


def _use_device(n):
    return settings.use_device_for(n)


def _fnv(mat, lens):
    # Measured on a real v5e (benchmarks/pallas_bench.py, round 3): the
    # Pallas VMEM-resident kernel (ops/pallas_fnv.py) runs at 0.58x the
    # portable _fnv_jit path (43.5 vs 74.7 Mtok/s at 128k x 16B tokens), so
    # there is no production dispatch to it — the kernel remains only as a
    # benchmarked negative result.
    n = mat.shape[0]
    if not _use_device(n):
        return _fnv_numpy(mat, lens)
    np_rows = _pow2_rows(n)
    if np_rows != n:
        mat = np.pad(mat, ((0, np_rows - n), (0, 0)))
        lens = np.pad(lens, (0, np_rows - n))
    h1, h2 = _fnv_jit()(mat, lens)
    return np.asarray(h1)[:n], np.asarray(h2)[:n]


def _mix_int(vals_i64):
    n = vals_i64.shape[0]
    if not _use_device(n):
        return _mix_int_numpy(vals_i64)
    np_rows = _pow2_rows(n)
    v = vals_i64
    if np_rows != n:
        v = np.pad(v, (0, np_rows - n))
    u = v.astype(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    h1, h2 = _mix_int_jit()(lo, hi)
    return np.asarray(h1)[:n], np.asarray(h2)[:n]


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------

def _canonical_int(k):
    """Map bools / integral floats to int to mirror Python equality grouping."""
    if isinstance(k, bool):
        return int(k)
    if isinstance(k, float) and k.is_integer():
        return int(k)
    return k


# Per-item key kinds.  Each kind maps to exactly one typed hash kernel, so a key
# hashes identically whether it appears in a homogeneous block or a mixed one
# (dispatching on the whole batch's type-set would route 'x' differently in a
# str-only block vs a str/int block — a shuffle-correctness bug).
_K_INT = 0     # bool / int in int64 range / integral float in range -> _mix_int
_K_STR = 1     # str / bytes -> dual-lane FNV over utf-8 bytes
_K_FBITS = 2   # non-integral or huge float -> _mix_int over float64 bit pattern
_K_OBJ = 3     # everything else -> deterministic canonical-bytes FNV

_I64_LO = -(2 ** 63)
_I64_HI = 2 ** 63 - 1


def _kind_of(k):
    if isinstance(k, np.generic):
        # numpy scalars (np.int64, np.bool_, np.float32, ...) classify by their
        # Python value — np.int64(5) must group with 5.
        k = k.item()
    if isinstance(k, bool):
        return _K_INT
    if isinstance(k, int):
        if _I64_LO <= k <= _I64_HI:
            return _K_INT
        # Out-of-range int: if exactly float-representable, hash as float bits
        # (Python equality: 10**300 == 1e300); else canonical-bytes lane.
        try:
            f = float(k)
        except OverflowError:
            return _K_OBJ
        return _K_FBITS if int(f) == k else _K_OBJ
    if isinstance(k, float):
        # Strict upper bound: 2.0**63 is float-representable but overflows
        # int64; anything strictly below converts exactly.
        if k.is_integer() and -(2.0 ** 63) <= k < 2.0 ** 63:
            return _K_INT
        return _K_FBITS
    if isinstance(k, (str, bytes)):
        return _K_STR
    return _K_OBJ


def encode_canonical(k):
    """Deterministic, type-tagged byte encoding of an arbitrary (hashable) key.

    Used for the object-lane hash: equal keys encode equally across processes
    and hosts (unlike Python's PYTHONHASHSEED-salted ``hash()``), so partition
    routing of tuple/frozenset keys is stable across spill-reload and multi-host
    boundaries.  Numeric leaves canonicalize exactly like the typed lanes
    (1 == 1.0 == True encode identically)."""
    if isinstance(k, np.generic):
        k = k.item()
    kind = _kind_of(k)
    if kind == _K_INT:
        return b"i" + str(int(_canonical_int(k))).encode("ascii")
    if kind == _K_FBITS:
        return b"f" + np.float64(k).tobytes()
    if kind == _K_STR:
        return (b"s" + k.encode("utf-8")) if isinstance(k, str) else (b"s" + bytes(k))
    if isinstance(k, int):
        # huge non-float-representable int
        return b"I" + str(k).encode("ascii")
    if k is None:
        return b"N"
    if isinstance(k, tuple):
        return b"(" + _join_lenprefixed(encode_canonical(x) for x in k)
    if isinstance(k, frozenset):
        return b"{" + _join_lenprefixed(sorted(encode_canonical(x) for x in k))
    # Last resort: repr (deterministic for well-behaved types).
    return b"r" + repr(k).encode("utf-8", "backslashreplace")


def _join_lenprefixed(encs):
    """Length-prefix each element encoding so composites are injective —
    ('a','b') and ('a\\x00sb',) must not encode identically."""
    out = bytearray()
    for e in encs:
        out += len(e).to_bytes(4, "little")
        out += e
    return bytes(out)


def _hash_bytes_list(bs):
    """(h1, h2) for a list of bytes keys: one native C pass when available
    (below the device-dispatch threshold), else the padded-matrix kernel.
    Both produce identical lanes by construction."""
    if not settings.use_device_for(len(bs)):
        from .. import native

        res = native.hash_bytes_batch(bs)
        if res is not None:
            return res
    mat, lens = encode_str_keys(bs)
    return _fnv(mat, lens)


def _hash_object_items(items):
    """Canonical-bytes FNV for a list of arbitrary keys -> (h1, h2)."""
    encs = [encode_canonical(_freeze(k)) for k in items]
    h1, h2 = _hash_bytes_list(encs)
    # Tag the object lane so b"i5" (a str key) and int 5's encoding can't be
    # confused with a real str key's hash by construction alone; collisions are
    # still resolved exactly downstream, this just keeps them rare.
    return h1 ^ np.uint32(0xA5A5A5A5), h2 ^ np.uint32(0x3C3C3C3C)


def _hash_kind(kind, items):
    """Run the single typed kernel for one homogeneous kind of keys.  Both the
    homogeneous fast path and the mixed-kind scatter path go through here, so a
    key's hash can never depend on which batch it arrived in."""
    n = len(items)
    if kind == _K_INT:
        return _mix_int(np.fromiter(
            (int(_canonical_int(k)) for k in items), dtype=np.int64, count=n))
    if kind == _K_STR:
        return _hash_bytes_list(
            [k.encode("utf-8") if isinstance(k, str) else bytes(k)
             for k in items])
    if kind == _K_FBITS:
        return _mix_int(np.fromiter(
            (float(k) for k in items), dtype=np.float64, count=n).view(np.int64))
    return _hash_object_items(items)


def hash_keys(keys):
    """Hash a batch of keys -> (h1, h2) uint32 arrays.

    `keys` is a numpy array (numeric dtype or object) or a list.  Dispatch is
    per item kind, so mixed-type blocks hash each key with the same typed
    kernel a homogeneous block would use (replaces the reference's per-record
    ``hash(key)`` — dampr/base.py:6-8 — with batched kernels).
    """
    if isinstance(keys, np.ndarray) and keys.dtype != object:
        if np.issubdtype(keys.dtype, np.integer) or keys.dtype == np.bool_:
            if keys.dtype == np.uint64 and len(keys) and keys.max() > np.uint64(_I64_HI):
                # astype(int64) would wrap; route through the per-item path so
                # uint64 2**63+1 hashes like the equal Python int.
                keys = keys.astype(object)
            else:
                return _mix_int(keys.astype(np.int64))
        elif np.issubdtype(keys.dtype, np.floating):
            return _hash_float_array(keys)
        else:
            # other dtypes (complex, datetime, ...): go through object path
            keys = keys.astype(object)

    keys = list(keys) if not isinstance(keys, np.ndarray) else keys
    n = len(keys)
    if n == 0:
        return (np.empty(0, dtype=np.uint32), np.empty(0, dtype=np.uint32))

    # Homogeneity probe: one C-level pass over the exact types.  A block of
    # all-str / in-range-int / plain-float keys — the overwhelmingly common
    # case — skips the per-item _kind_of loop entirely.  Every branch routes
    # into the same typed kernels (_hash_kind / _hash_float_array) the
    # per-item path would pick, so hashes are identical by construction.
    ts = set(map(type, keys))
    if ts == {str} or ts == {bytes}:
        return _hash_kind(_K_STR, keys)
    if ts == {bool}:
        return _mix_int(np.fromiter(keys, dtype=np.int64, count=n))
    if ts == {int}:
        try:
            return _mix_int(np.fromiter(keys, dtype=np.int64, count=n))
        except OverflowError:
            pass  # out-of-int64 ints present: per-item classification
    elif ts == {float}:
        return _hash_float_array(np.fromiter(keys, dtype=np.float64, count=n))

    kinds = np.empty(n, dtype=np.int8)
    for i, k in enumerate(keys):
        kinds[i] = _kind_of(k)

    uniq = set(kinds.tolist())
    if len(uniq) == 1:
        return _hash_kind(uniq.pop(), keys)

    # Mixed kinds: hash each homogeneous sub-batch with its typed kernel and
    # scatter results back into place.
    h1 = np.empty(n, dtype=np.uint32)
    h2 = np.empty(n, dtype=np.uint32)
    for kind in uniq:
        idx = np.flatnonzero(kinds == kind)
        a, b = _hash_kind(kind, [keys[i] for i in idx])
        h1[idx] = a
        h2[idx] = b
    return h1, h2


def _hash_float_array(arr):
    """Float keys: integral in-int64-range values canonicalize to ints (Python
    equality: 1.0 groups with 1); the rest hash on their float64 bit pattern.
    Bounds match ``_kind_of`` exactly so container type never changes a hash."""
    arr64 = arr.astype(np.float64)
    integral = ((arr64 == np.floor(arr64)) & np.isfinite(arr64)
                & (arr64 >= -(2.0 ** 63)) & (arr64 < 2.0 ** 63))
    as_int = np.where(integral, arr64, 0).astype(np.int64)
    bits = arr64.view(np.int64)
    mixed_src = np.where(integral, as_int, bits)
    return _mix_int(mixed_src)


def _freeze(k):
    if isinstance(k, list):
        return tuple(_freeze(x) for x in k)
    if isinstance(k, dict):
        return tuple(sorted((kk, _freeze(vv)) for kk, vv in k.items()))
    if isinstance(k, set):
        return frozenset(k)
    return k


def combine64(h1, h2):
    """Combine the two uint32 lanes into one uint64 per record (host only)."""
    return (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
