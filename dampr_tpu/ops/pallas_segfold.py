"""Pallas TPU kernel: fused segmented fold over hash-sorted records.

After the engine sorts records by (validity, h1, h2), the scan lowering in
:func:`dampr_tpu.parallel.shuffle._local_fold` computes per-segment totals
at segment-end positions with ~6 separate XLA passes (boundary compare,
shift, cumsum, cummax, two selects) — each a full HBM round-trip.  This
kernel fuses the whole post-sort chain into ONE pass: each grid step pulls
one tile of (h1, h2, v, inv) into VMEM, computes the flattened prefix sum
and the carried segment-start offset in-register, and writes the totals and
liveness mask; scalar carry state (previous element's keys/validity, the
running global prefix, the last segment-start's exclusive prefix) rides
SMEM across the sequential grid.

Lookahead: an element is a segment *end* iff the next element starts a new
segment, so the kernel reads a second view of the key arrays offset one
tile ahead (same buffers, shifted index_map) to see the first element of
the next tile; the final tile treats "next" as different (last element of
the array is always an end).

Exactness contract: identical to the scan lowering — nonnegative integer
values whose global sum fits the lane dtype (callers guarantee it: see
`mesh_keyed_fold`'s `nonneg` predicate), so the running prefix cannot wrap
and subtraction of exclusive prefixes is exact.

Like ops/pallas_fnv.py this is TPU-Mosaic code; CPU tests run it with
``interpret=True``.  The real-chip benchmark lives in
benchmarks/pallas_bench.py and RESULTS.md records whether it beats the XLA
scan chain (no unverified perf claims here).

Reference anchor: this is the hot half of the reference's combine path
(dampr/base.py:393-402 PartialReduceCombiner + dataset.py:84-117
ReducedWriter) — per-key accumulation — executed as one device pass.
"""

import functools

import numpy as np

_LANES = 128
_ROWS = 64  # 64 x 128 = 8192 records per tile (4 uint32 tiles = 128KB VMEM)


def _tile_elems():
    return _ROWS * _LANES


def _build_kernel():
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    def corner(x, r, l):
        """Scalar at static position (r, l) of a tile.  jnp integer
        indexing (``x[-1, -1]``) lowers through ``dynamic_slice`` even for
        constant indices, which this Mosaic version does not implement;
        a static ``lax.slice`` + single-element reduce does."""
        r = r % x.shape[0]
        l = l % x.shape[1]
        assert jnp.issubdtype(x.dtype, jnp.signedinteger), (
            "corner() requires signed tiles: Mosaic lacks unsigned "
            "reductions (callers convert hash/validity lanes to int32)")
        return jnp.sum(lax.slice(x, (r, l), (r + 1, l + 1)))

    def _iotas(shape):
        ri = lax.broadcasted_iota(jnp.int32, shape, 0)
        li = lax.broadcasted_iota(jnp.int32, shape, 1)
        return ri, li

    def shift_one(x, first):
        """Flattened-order shift-by-one of an (R, L) tile: element (r, l)
        receives (r, l-1), row starts receive the previous row's last lane,
        and (0, 0) receives ``first`` (the carried previous element).

        Built from ``pltpu.roll`` + iota masks: Mosaic rejects the natural
        width-1 column concatenates ("offset mismatch on non-concat
        dimension"), but full-tile rotates lower cleanly."""
        from jax.experimental.pallas import tpu as pltpu

        lane = pltpu.roll(x, 1, axis=1)       # (r, l) <- (r, (l-1) % L)
        wrap = pltpu.roll(lane, 1, axis=0)    # at l==0: (r, 0) <- (r-1, L-1)
        ri, li = _iotas(x.shape)
        s = jnp.where(li == 0, wrap, lane)
        return jnp.where((li == 0) & (ri == 0), first, s)

    def _scan(x, op, pad, axis):
        """Inclusive Hillis-Steele scan along one axis of a 2-D tile.
        Mosaic's TC lowering (this jax version) has no cumsum/cummax
        primitive, so the scan is log-depth shifted-operand steps built
        from concatenate/slice — which do lower."""
        n = x.shape[axis]
        d = 1
        while d < n:
            if axis == 1:
                pads = jnp.full((x.shape[0], d), pad, x.dtype)
                shifted = jnp.concatenate([pads, x[:, :-d]], axis=1)
            else:
                pads = jnp.full((d, x.shape[1]), pad, x.dtype)
                shifted = jnp.concatenate([pads, x[:-d, :]], axis=0)
            x = op(x, shifted)
            d *= 2
        return x

    def flat_cumsum(x):
        """Inclusive prefix sum of an (R, L) int32 tile in flattened
        row-major order: lane scan + carried row offsets."""
        row = _scan(x, jnp.add, 0, axis=1)
        # per-row totals broadcast across lanes, then scanned over rows so
        # the sublane scan runs at full lane width (a (R, 1) operand would
        # fight the (8, 128) tiling)
        row_tot = jnp.broadcast_to(row[:, -1:], x.shape)
        row_off_incl = _scan(row_tot, jnp.add, 0, axis=0)
        return row + (row_off_incl - row_tot)

    def flat_cummax(x):
        """Inclusive prefix max, flattened row-major order."""
        neg = jnp.iinfo(x.dtype).min
        row = _scan(x, jnp.maximum, neg, axis=1)
        row_max = jnp.broadcast_to(row[:, -1:], x.shape)
        row_carry = _scan(row_max, jnp.maximum, neg, axis=0)
        prev_rows = jnp.concatenate(
            [jnp.full((1, x.shape[1]), neg, x.dtype), row_carry[:-1]],
            axis=0)
        return jnp.maximum(row, prev_rows)

    def kernel(h1_ref, h2_ref, v_ref, inv_ref, nh1_ref, nh2_ref, ninv_ref,
               tot_ref, live_ref, carry_ref):
        # carry_ref (SMEM int64-free: 5 x int32-compatible slots):
        # [0] prev_h1 (as int32 bits), [1] prev_h2, [2] prev_inv,
        # [3] running exclusive prefix, [4] exclusive prefix at the
        #     current segment's start
        i = pl.program_id(0)
        n_i = pl.num_programs(0)

        @pl.when(i == 0)
        def _():
            carry_ref[0] = jnp.int32(0)
            carry_ref[1] = jnp.int32(0)
            carry_ref[2] = jnp.int32(2)  # impossible validity: forces start
            carry_ref[3] = jnp.int32(0)
            carry_ref[4] = jnp.int32(0)

        # All key/validity logic runs in int32 bitspace (same-width integer
        # conversion is modular, so equality is preserved): Mosaic lacks
        # unsigned reductions and some unsigned selects.
        h1 = h1_ref[:].astype(jnp.int32)
        h2 = h2_ref[:].astype(jnp.int32)
        v = v_ref[:]
        inv = inv_ref[:].astype(jnp.int32)

        ph1 = shift_one(h1, carry_ref[0].astype(h1.dtype))
        ph2 = shift_one(h2, carry_ref[1].astype(h2.dtype))
        pinv = shift_one(inv, carry_ref[2].astype(inv.dtype))
        starts = (h1 != ph1) | (h2 != ph2) | (inv != pinv)

        run = carry_ref[3]
        prefix = flat_cumsum(v) + run          # inclusive global prefix
        ex = prefix - v                        # exclusive global prefix

        # Exclusive prefix at each element's segment start: carried value
        # until the first start in this tile, then a running max of start
        # positions' ex (monotone because v >= 0).
        neg = jnp.iinfo(jnp.int32).min
        marked = jnp.where(starts, ex, neg)
        run_start_ex = jnp.maximum(flat_cummax(marked), carry_ref[4])

        # Ends: the next element (flattened order, with one-tile lookahead)
        # begins a new segment.  next_* of the last element comes from the
        # lookahead view; on the final tile it is forced different.
        last = n_i - 1
        # the forced "next" must differ from the LAST element so the
        # array's final record is always an end; +1 wraps and so always
        # differs in the h1 lane
        nxt_h1 = jnp.where(i == last, corner(h1, -1, -1) + 1,
                           corner(nh1_ref[:].astype(jnp.int32), 0, 0))
        nxt_h2 = jnp.where(i == last, corner(h2, -1, -1),
                           corner(nh2_ref[:].astype(jnp.int32), 0, 0))
        nxt_inv = jnp.where(i == last, jnp.int32(3),
                            corner(ninv_ref[:].astype(jnp.int32), 0, 0))
        nh1s = shift_back(h1, nxt_h1)
        nh2s = shift_back(h2, nxt_h2)
        ninvs = shift_back(inv, nxt_inv)
        ends = (h1 != nh1s) | (h2 != nh2s) | (inv != ninvs)

        tot_ref[:] = jnp.where(ends, prefix - run_start_ex, 0).astype(
            tot_ref.dtype)
        live_ref[:] = jnp.where(
            ends & (inv == 0), 1, 0).astype(live_ref.dtype)

        # Update carries for the next tile.
        carry_ref[0] = corner(h1, -1, -1).astype(jnp.int32)
        carry_ref[1] = corner(h2, -1, -1).astype(jnp.int32)
        carry_ref[2] = corner(inv, -1, -1).astype(jnp.int32)
        carry_ref[3] = corner(prefix, -1, -1)
        carry_ref[4] = corner(run_start_ex, -1, -1)

    def shift_back(x, nxt):
        """Flattened-order shift-backward-by-one: element (r, l) receives
        (r, l+1); row ends receive the next row's first lane; the tile's
        last element receives ``nxt``.  Same roll+mask construction as
        :func:`shift_one` (rolls take non-negative shifts: size-1 = -1)."""
        from jax.experimental.pallas import tpu as pltpu

        R, L = x.shape
        lane = pltpu.roll(x, L - 1, axis=1)   # (r, l) <- (r, (l+1) % L)
        wrap = pltpu.roll(lane, R - 1, axis=0)  # at l==L-1: <- (r+1, 0)
        ri, li = _iotas(x.shape)
        s = jnp.where(li == L - 1, wrap, lane)
        return jnp.where((li == L - 1) & (ri == R - 1), nxt, s)

    return kernel


@functools.lru_cache(maxsize=None)
def _segfold_call(n_tiles, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = _build_kernel()
    R, L = _ROWS, _LANES

    def tile_map(i):
        return (i, 0)

    def next_tile_map(i):
        # lookahead view: the first sublane-aligned row block of the next
        # tile (only its [0, 0] element is read), clamped on the final tile
        # (its values are ignored there — the kernel forces a difference).
        # Index units are (8, L) blocks: one tile spans R // 8 of them.
        per_tile = R // 8
        return (jnp.minimum((i + 1) * per_tile, n_tiles * per_tile - 1), 0)

    def call(h1, h2, v, inv):
        return pl.pallas_call(
            kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((R, L), tile_map),
                pl.BlockSpec((R, L), tile_map),
                pl.BlockSpec((R, L), tile_map),
                pl.BlockSpec((R, L), tile_map),
                pl.BlockSpec((8, L), next_tile_map),
                pl.BlockSpec((8, L), next_tile_map),
                pl.BlockSpec((8, L), next_tile_map),
            ],
            out_specs=[
                pl.BlockSpec((R, L), tile_map),
                pl.BlockSpec((R, L), tile_map),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_tiles * R, L), jnp.int32),
                jax.ShapeDtypeStruct((n_tiles * R, L), jnp.uint32),
            ],
            scratch_shapes=[pltpu.SMEM((5,), jnp.int32)],
            interpret=interpret,
        )(h1, h2, v, inv, h1, h2, inv)

    return jax.jit(call)


def segfold_sorted(h1, h2, v, inv, interpret=False):
    """Per-segment totals of hash-sorted records, one fused device pass.

    Inputs are 1-D device or host arrays sorted by (inv, h1, h2): uint32
    hash lanes, int32 nonneg values, uint32 validity (0 = valid).  Returns
    (tot, live) 1-D arrays: ``tot[j]`` is the segment total where ``j`` is
    the segment's last position and ``live[j] == 1``; 0/0 elsewhere.  The
    caller pads to a multiple of the tile size with invalid rows.
    """
    import jax.numpy as jnp

    n = len(h1)
    te = _tile_elems()
    assert n % te == 0, "caller pads to a multiple of %d" % te
    n_tiles = n // te
    R, L = _ROWS, _LANES
    shape = (n_tiles * R, L)
    call = _segfold_call(n_tiles, interpret)
    tot, live = call(
        jnp.asarray(h1).reshape(shape), jnp.asarray(h2).reshape(shape),
        jnp.asarray(v).reshape(shape), jnp.asarray(inv).reshape(shape))
    return jnp.asarray(tot).reshape(n), jnp.asarray(live).reshape(n)


def segfold_reference(h1, h2, v, inv):
    """Host oracle for tests: exact per-segment totals at end positions."""
    n = len(h1)
    tot = np.zeros(n, dtype=np.int64)
    live = np.zeros(n, dtype=np.uint32)
    at = 0
    while at < n:
        end = at
        while (end + 1 < n and h1[end + 1] == h1[at]
               and h2[end + 1] == h2[at] and inv[end + 1] == inv[at]):
            end += 1
        tot[end] = int(v[at:end + 1].sum())
        live[end] = 1 if inv[at] == 0 else 0
        at = end + 1
    return tot, live
