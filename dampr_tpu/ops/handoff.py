"""Cross-stage device-resident handoff: the per-job vocabulary tier.

This is the execution half of the plan's ``handoff="device"`` edge
decision (:mod:`dampr_tpu.plan.lower`): when a lowered scanner map feeds
a device-lowered associative sum fold, the map's program outputs never
round-trip through the host spill path (d2h fetch -> host combine ->
pickle -> frame encode -> spill -> re-read -> h2d).  Instead each job
keeps a device-resident vocabulary:

- ``acc``        — per-slot count accumulator (int32 lanes, int64 under
  x64), updated in place by every batch (buffer donation where the
  backend supports it);
- ``tab_h1``/``tab_slot`` — the sorted hash-lookup lanes batches probe
  with one vectorized ``searchsorted``;
- ``tab_mat``/``tab_lens`` — the vocabulary's raw byte rows, so every
  probe HIT is verified byte-for-byte inside the program (a 64-bit — or
  32-bit — hash collision can never merge distinct tokens: mismatching
  bytes route to the exact host miss path instead).

Batches whose tokens are mostly in the table run the **table program**:
single-lane FNV + searchsorted + byte verify + (for per-line dedup) a
two-lane ``(slot, line)`` sort + scatter-add — roughly a third of the
classic program's cost, because the five-lane ``lax.sort`` over the full
token stream disappears.  Early batches (and vocabulary-shift phases)
bootstrap through the classic hash->sort->segment program
(:mod:`.lower`), whose drained survivors seed the table; on the CPU
backend the job's first whole window seeds it through the native host
codec instead (:func:`_host_bootstrap` — cached hash lanes, no
re-hash, the window's tokenize/pad/dispatch skipped outright).

At job end the accumulator becomes per-partition HBM-resident
:class:`~dampr_tpu.storage.BlockRef` s (``BlockRef.from_device_lanes``)
that the consuming fold (``runner._mesh_reduce``) consumes in place.

Exactness contract: every count lands in a slot either (a) verified
byte-identical to the slot's bytes inside a program, or (b) through the
host miss/fallback path keyed by canonical UTF-8 bytes.  Degrades — HBM
budget exceeded, int32 overflow risk, vocabulary overflow — flush the
accumulator into one hash-sorted host block and hand the rest of the job
to the classic spill path, byte-identically.
"""

import functools
import logging

import numpy as np

from .. import settings
from ..obs import trace as _trace
from . import devtime

log = logging.getLogger("dampr_tpu.ops.handoff")

#: Classic-drain lane bytes per padded slot the table program never
#: fetches: sh1 (4) + sh2 (4) + tot (4) + live (1) + rep_orig (4).
CLASSIC_DRAIN_BYTES_PER_SLOT = 17

#: Bootstrap heuristic: a classic batch whose NEW-vocabulary-slots-per-
#: batch-token fraction falls under the enter bar switches the job to
#: the table program; a table batch whose miss fraction exceeds the
#: revert bar switches back (vocabulary shift).  Both signals estimate
#: the same quantity — the next batch's miss rate, whose host cost is
#: roughly the classic per-token cost — so the bars sit where the
#: table's drain saving (17 bytes/slot never fetched) beats the miss
#: path; enter is slightly stricter than revert for hysteresis.  On
#: Zipf text one 256k-token classic batch seeds ~93% token coverage
#: (new_frac ~0.07), so jobs engage after their FIRST drain.  Pure
#: performance knobs — results are identical either way.
_TABLE_ENTER_NEW_FRAC = 0.20
_TABLE_REVERT_MISS_FRAC = 0.25

_I32_GUARD = 1 << 30
_I64_GUARD = 1 << 62


def _pow2(n, floor=4096):
    return max(floor, 1 << max(0, (int(n) - 1).bit_length()))


@functools.lru_cache(maxsize=None)
def _donate_ok():
    """Buffer donation is a no-op (with a warning) on CPU backends;
    donate only where shapes and platform permit."""
    import jax

    return jax.default_backend() not in ("cpu",)


@functools.lru_cache(maxsize=None)
def _acc_dtype():
    import jax

    return np.dtype(np.int64 if jax.config.jax_enable_x64 else np.int32)


@functools.lru_cache(maxsize=None)
def _host_bootstrap():
    """On the CPU backend the classic bootstrap program is pure
    overhead: its five-lane ``lax.sort`` runs on the very cores the
    native host codec would use at ~20x the throughput — so an
    empty-vocabulary job seeds the table from its FIRST WHOLE WINDOW
    through that codec (whose blocks carry cached hash lanes: no
    re-hash, no row sort), skipping the window's tokenize/pad/dispatch
    entirely.  A real accelerator keeps the classic bootstrap: the
    program runs on device while the host tokenizes the next window."""
    import jax

    return jax.default_backend() == "cpu"


#: Windowed-dedup span (tokens): batches whose longest line fits run
#: the shifted-compare dedup (K passes over the batch) instead of the
#: ~4x-costlier (slot, line) sort; the host picks the variant per batch
#: from the actual max tokens-per-line (``dedup_k=0`` = sort).
_DEDUP_WINDOW = 16


@functools.lru_cache(maxsize=None)
def _table_program(n, L, cap, Lcap, dedup, acc_dtype_name, dedup_k=0):
    """One compiled probe-and-count program per shape bucket.

    hash (single FNV lane) -> searchsorted into the sorted table ->
    byte-verified hit mask -> dedup'd (slot, line) scatter-add into the
    donated accumulator.  Returns (acc, miss mask, miss count); misses
    (new vocabulary, hash duplicates, byte mismatches) are handled
    exactly on the host.

    ``dedup_k > 0``: every line in the batch spans at most ``dedup_k``
    tokens (host-verified per batch), so a duplicate (slot, line) pair
    sits within ``dedup_k`` positions of its first occurrence — K
    shifted compares replace the full (slot, line) sort."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .hashing import _FNV_OFFSET1, _FNV_PRIME1

    W = min(L, Lcap)

    def program(mat, lens, lines, tab_h1, tab_slot, tab_mat, tab_lens,
                acc):
        h1 = jnp.full((n,), _FNV_OFFSET1, dtype=jnp.uint32)

        def body(c, a):
            active = c < lens
            byte = mat[:, c].astype(jnp.uint32)
            return jnp.where(active, (a ^ byte) * _FNV_PRIME1, a)

        h1 = lax.fori_loop(0, L, body, h1)

        pos = jnp.clip(jnp.searchsorted(tab_h1, h1), 0, cap - 1)
        cand = jnp.take(tab_slot, pos)
        valid = lens > 0
        same = valid & (jnp.take(tab_h1, pos) == h1) \
            & (jnp.take(tab_lens, cand) == lens)
        rep = jnp.take(tab_mat, cand, axis=0)
        if Lcap > W:
            rep = lax.slice(rep, (0, 0), (n, W))
        mw = mat if L == W else lax.slice(mat, (0, 0), (n, W))
        # Byte columns past a token's length are zero in BOTH the batch
        # matrix and the table rows, and lengths already matched, so a
        # W-column compare is a full-token compare.
        same = same & jnp.all(rep == mw, axis=1)
        miss = valid & ~same
        sink = jnp.int32(cap)
        if dedup and dedup_k:
            # Per-line first occurrence, windowed: line ids are
            # non-decreasing (tokens arrive in document order) and no
            # line spans more than dedup_k tokens, so a duplicate
            # (slot, line) pair lies within dedup_k positions of its
            # first occurrence — K shifted compares beat the sort ~4x.
            slot_key = jnp.where(same, cand, sink)
            li = lines.astype(jnp.int32)
            dup = jnp.zeros((n,), dtype=bool)
            for k in range(1, dedup_k + 1):
                dup = dup.at[k:].set(
                    dup[k:] | ((slot_key[k:] == slot_key[:-k])
                               & (li[k:] == li[:-k])
                               & (slot_key[k:] < sink)))
            contrib = jnp.where(~dup & (slot_key < sink), 1, 0)
            acc = acc.at[slot_key].add(contrib.astype(acc.dtype))
        elif dedup:
            # Per-line first occurrence (DocFreq): sort hits by
            # (slot, line) — two int32 lanes instead of the classic
            # five-lane token sort — and count segment starts per slot.
            slot_key = jnp.where(same, cand, sink)
            s_slot, s_line = lax.sort(
                (slot_key, lines.astype(jnp.int32)), num_keys=2,
                is_stable=False)
            first = jnp.ones((n,), dtype=bool).at[1:].set(
                (s_slot[1:] != s_slot[:-1]) | (s_line[1:] != s_line[:-1]))
            contrib = jnp.where(first & (s_slot < sink), 1, 0)
            acc = acc.at[s_slot].add(contrib.astype(acc.dtype))
        else:
            acc = acc.at[jnp.where(same, cand, sink)].add(
                jnp.where(same, 1, 0).astype(acc.dtype))
        return acc, miss, jnp.sum(miss.astype(jnp.int32))

    kwargs = {"donate_argnums": (7,)} if _donate_ok() else {}
    return jax.jit(program, **kwargs)


@functools.lru_cache(maxsize=None)
def _scatter_program():
    """Host-side contributions (bootstrap drains, misses, long tokens,
    fallback windows) fold into the accumulator with one scatter-add."""
    import jax

    kwargs = {"donate_argnums": (0,)} if _donate_ok() else {}
    return jax.jit(lambda acc, slots, vals: acc.at[slots].add(vals),
                   **kwargs)


def group_token_rows(buf, starts, lens, lines, dedup):
    """Exact host grouping of a token subset: length-prefixed byte rows
    through ``np.unique`` — colliding hashes can never merge distinct
    tokens — with per-line first-occurrence dedup when ``dedup``.
    Returns ``(uniq_rows, counts)``; ``uniq_rows[i, 0]`` is the token
    length, its bytes follow.  The ONE copy of this algorithm: both the
    classic collision fallback (``lower._host_batch``) and the handoff
    miss path absorb through it, so their byte-identity can never drift
    apart.  MIRROR of ``text._numpy_counts_block``'s short-token path
    parameterized on precomputed bounds — a semantic change to either
    grouping MUST land in both, or the equivalence suite's parity pins
    will catch it."""
    n = len(starts)
    L = int(lens.max())
    idx = starts[:, None] + np.arange(L, dtype=np.int64)[None, :]
    np.clip(idx, 0, len(buf) - 1, out=idx)
    mat = np.where(np.arange(L, dtype=np.int32)[None, :]
                   < lens[:, None], buf[idx], 0)
    rows = np.empty((n, L + 1), dtype=np.uint8)
    rows[:, 0] = lens
    rows[:, 1:] = mat
    uniq, inverse = np.unique(rows, axis=0, return_inverse=True)
    inverse = inverse.reshape(-1)
    if dedup:
        combined = lines.astype(np.int64) * len(uniq) + inverse
        uc = np.unique(combined)
        counts = np.bincount(uc % len(uniq), minlength=len(uniq))
    else:
        counts = np.bincount(inverse, minlength=len(uniq))
    return uniq, counts


class _TableBatch(object):
    """One in-flight table-program dispatch (the double-buffer handle).
    ``miss_idx`` caches the fetched miss positions so a drain that
    degrades (or resolves after a degrade) can hand the missed tokens to
    the exact host emit path without re-fetching."""

    __slots__ = ("miss", "n_miss", "starts", "lens", "lines", "n",
                 "npad", "miss_idx")

    def __init__(self, miss, n_miss, starts, lens, lines, n, npad):
        self.miss = miss
        self.n_miss = n_miss
        self.starts = starts
        self.lens = lens
        self.lines = lines
        self.n = n
        self.npad = npad
        self.miss_idx = None


class HandoffVocab(object):
    """Per-job device-resident vocabulary + accumulator (one per lowered
    handoff-edge map job; never shared across jobs or threads).

    ``budget`` is THIS vocabulary's share of the run's handoff budget:
    the runner divides ``settings.effective_handoff_budget()`` by the
    stage's concurrent job count, so N parallel jobs can never hold
    N x budget of device memory between them (each job degrades
    gracefully at its share instead of the fleet hitting an allocator
    failure)."""

    def __init__(self, store, dedup, budget=None):
        self.store = store
        self.dedup = dedup
        self.budget = (int(budget) if budget is not None
                       else settings.effective_handoff_budget())
        self.nslots = 0
        self.cap = 0
        self.Lcap = 8
        self.bytes2slot = {}
        self.keys = []        # decoded str per slot
        self.slot_bytes = []  # canonical utf-8 bytes per slot
        self.h1 = []          # python ints (host lanes)
        self.h2 = []
        self._tab_dirty = True
        self._lanes_forced = False  # regrow reallocated the lanes
        self._lanes_deferred = 0    # slots inserted since last rebuild
        self._pending_rows = []   # (slot, bytes) not yet on device
        self.acc = None
        self.tab_h1 = None
        self.tab_slot = None
        self.tab_mat = None
        self.tab_lens = None
        self.total_added = 0
        self.table_mode = False
        self.degraded = False
        self.degrade_reason = None
        self.table_batches = 0
        self.classic_batches = 0

    # -- capacity ----------------------------------------------------------
    def _guard(self):
        return _I64_GUARD if _acc_dtype() == np.int64 else _I32_GUARD

    def device_bytes(self):
        if self.cap == 0:
            return 0
        return int(self.cap * (self.Lcap + 4 + 4 + 4)
                   + (self.cap + 1) * _acc_dtype().itemsize)

    def _ensure_capacity(self, need_slots, need_len):
        """Grow the device table (pow2 slots, pow2 byte width).  Returns
        False when growth would exceed the handoff budget — the caller
        degrades.  Row width never grows past the probe-able maximum:
        batches only carry tokens <= ``text._SHORT_TOKEN`` bytes, and a
        hit requires ``tab_lens == lens``, so a LONGER token's row can
        never verify — such tokens get a slot, hash lanes, and an
        accumulator row, but their stored bytes truncate (dead for
        probing either way) instead of widening every slot's row."""
        import jax.numpy as jnp

        from .text import _SHORT_TOKEN

        new_cap = self.cap
        while need_slots > (new_cap or 0):
            new_cap = _pow2(max(need_slots, 4096, (new_cap or 0) * 2))
        new_L = self.Lcap
        while need_len > new_L and new_L < _SHORT_TOKEN + 1:
            new_L *= 2
        if new_cap == self.cap and new_L == self.Lcap:
            return True
        projected = int(new_cap * (new_L + 12)
                        + (new_cap + 1) * _acc_dtype().itemsize)
        if projected > self.budget:
            return False
        old_acc, old_nslots = self.acc, self.nslots
        self.acc = jnp.zeros(new_cap + 1, dtype=_acc_dtype())
        if old_acc is not None and old_nslots:
            self.acc = self.acc.at[:old_nslots].set(old_acc[:old_nslots])
        self.tab_mat = jnp.zeros((new_cap, new_L), dtype=jnp.uint8)
        self.tab_lens = jnp.full((new_cap,), -1, dtype=jnp.int32)
        self.cap = new_cap
        self.Lcap = new_L
        # Re-stage every row: the widened/regrown matrices start empty.
        self._pending_rows = list(enumerate(self.slot_bytes))
        self._tab_dirty = True
        # The lookup lanes were sized for the old cap: the next sync
        # MUST rebuild them (the program bucket keys on cap).
        self._lanes_forced = True
        return True

    def _sync_table(self):
        """Publish staged host rows + the sorted lookup lanes to device
        (h2d charged for what actually moves)."""
        import jax
        import jax.numpy as jnp

        moved = 0
        if self._pending_rows:
            slots = np.fromiter((s for s, _b in self._pending_rows),
                                dtype=np.int32,
                                count=len(self._pending_rows))
            rows = np.zeros((len(slots), self.Lcap), dtype=np.uint8)
            lens = np.empty(len(slots), dtype=np.int32)
            for i, (_s, b) in enumerate(self._pending_rows):
                # Rows wider than Lcap truncate: their true length in
                # tab_lens already fails every probe's length check
                # (batch tokens are <= _SHORT_TOKEN <= Lcap's bound).
                w = min(len(b), self.Lcap)
                rows[i, :w] = np.frombuffer(b[:w], dtype=np.uint8)
                lens[i] = len(b)
            dslots = jnp.asarray(slots)
            self.tab_mat = self.tab_mat.at[dslots].set(jnp.asarray(rows))
            self.tab_lens = self.tab_lens.at[dslots].set(jnp.asarray(lens))
            moved += rows.nbytes + lens.nbytes
            self._pending_rows = []
        if self._tab_dirty and (
                self.tab_h1 is None or self._lanes_forced
                or self._lanes_deferred >= max(1024, self.nslots >> 4)):
            # One argsort per REBUILD beats per-insert sorted-array
            # maintenance (np.insert is a full copy — O(vocab^2) across a
            # bootstrap) — and rebuilds themselves are deferred until
            # enough slots accumulated (~6% of the vocabulary), because
            # each one re-sorts and re-uploads the whole cap-sized lane
            # pair.  Deferral is exact: a slot absent from the lanes
            # simply keeps MISSING to the host absorb path, which finds
            # it in ``bytes2slot`` and scatters into the same
            # accumulator row.  A regrow always rebuilds (the lanes were
            # reallocated for the new cap).  Pad positions carry the max
            # hash; a bogus hit there fails the byte/length verify, so
            # no validity lane is needed.
            h1a = np.asarray(self.h1, dtype=np.uint32)
            order = np.argsort(h1a, kind="stable")
            th1 = np.full(self.cap, np.uint32(0xFFFFFFFF),
                          dtype=np.uint32)
            th1[:len(order)] = h1a[order]
            tsl = np.zeros(self.cap, dtype=np.int32)
            tsl[:len(order)] = order
            self.tab_h1 = jax.device_put(th1)
            self.tab_slot = jax.device_put(tsl)
            moved += th1.nbytes + tsl.nbytes
            self._tab_dirty = False
            self._lanes_forced = False
            self._lanes_deferred = 0
        if moved and self.store is not None:
            self.store.count_h2d(moved)

    # -- host-side insert/lookup -------------------------------------------
    def _insert(self, raw, key, h1, h2):
        """New slot for canonical bytes ``raw`` (caller checked absence).
        Returns the slot, or -1 when the table cannot grow (degrade)."""
        if not self._ensure_capacity(self.nslots + 1, len(raw)):
            return -1
        slot = self.nslots
        self.nslots += 1
        self.bytes2slot[raw] = slot
        self.slot_bytes.append(raw)
        self.keys.append(key)
        self.h1.append(int(h1))
        self.h2.append(int(h2))
        self._pending_rows.append((slot, raw))
        self._tab_dirty = True
        self._lanes_deferred += 1
        return slot

    def lookup_or_insert(self, raws, keys=None, h1=None, h2=None):
        """Slots for a list of canonical utf-8 byte strings; unseen ones
        insert (hash lanes computed here unless provided).  Returns an
        int32 array, or None when the table refused to grow."""
        from . import hashing

        slots = np.empty(len(raws), dtype=np.int32)
        new_at = [i for i, b in enumerate(raws)
                  if b not in self.bytes2slot]
        if new_at and (keys is None or h1 is None):
            nk = np.empty(len(new_at), dtype=object)
            for j, i in enumerate(new_at):
                nk[j] = raws[i].decode("utf-8", "replace")
            nh1, nh2 = hashing.hash_keys(nk)
            for j, i in enumerate(new_at):
                s = self._insert(raws[i], nk[j], nh1[j], nh2[j])
                if s < 0:
                    return None
        elif new_at:
            for i in new_at:
                s = self._insert(raws[i], keys[i], h1[i], h2[i])
                if s < 0:
                    return None
        get = self.bytes2slot.get
        for i, b in enumerate(raws):
            slots[i] = get(b)
        return slots

    # -- count flow --------------------------------------------------------
    def scatter_counts(self, slots, counts):
        """Fold host-side per-slot contributions into the accumulator."""
        import jax.numpy as jnp

        if not len(slots):
            return True
        total = int(np.asarray(counts, dtype=np.int64).sum())
        if self.total_added + total > self._guard():
            return False
        self.total_added += total
        self._sync_table()
        self.acc = _scatter_program()(
            self.acc, jnp.asarray(np.asarray(slots, dtype=np.int32)),
            jnp.asarray(np.asarray(counts).astype(_acc_dtype())))
        if self.store is not None:
            self.store.count_h2d(len(slots) * (4 + _acc_dtype().itemsize))
        return True

    def absorb_block(self, blk):
        """Fold a host-path block (long tokens, fallback windows,
        collision regroups) into the accumulator — keyed by the decoded
        key's canonical utf-8 bytes, same as the device rows.  Returns
        False when the job must degrade."""
        h1, h2 = blk.hashes()
        keys = blk.keys
        raws = [None] * len(keys)
        for i in range(len(keys)):
            raws[i] = keys[i].encode("utf-8")
        slots = self.lookup_or_insert(raws, keys=keys, h1=h1, h2=h2)
        if slots is None:
            return False
        return self.scatter_counts(slots, blk.values)

    def absorb_drain(self, keys, counts, h1, h2, batch_tokens):
        """Seed the table from a classic-program drain's survivors and
        fold their counts (the bootstrap path).  Returns (ok,
        new_fraction) — NEW vocabulary slots per batch TOKEN, the
        table-mode switch signal: the miss path's cost scales with the
        tokens that would miss, and new vocabulary under a Zipf tail is
        rare per token even while it is common per distinct key."""
        raws = [None] * len(keys)
        for i in range(len(keys)):
            raws[i] = keys[i].encode("utf-8")
        before = self.nslots
        slots = self.lookup_or_insert(raws, keys=keys, h1=h1, h2=h2)
        if slots is None:
            return False, 0.0
        new_frac = ((self.nslots - before) / float(batch_tokens)
                    if batch_tokens else 0.0)
        return self.scatter_counts(slots, counts), new_frac

    # -- the table-mode batch ----------------------------------------------
    def dispatch(self, mat, lens_p, lines_p, starts, lens, lines, n):
        """Launch the probe-and-count program over one padded batch; the
        accumulator advances asynchronously (double-buffered like the
        classic dispatch).  Returns the drain handle, or None when the
        job must degrade (overflow guard)."""
        import jax.numpy as jnp

        npad, L = mat.shape
        if self.total_added + n > self._guard():
            return None
        if not self._ensure_capacity(max(self.nslots, 1), self.Lcap):
            return None
        self._sync_table()
        self.total_added += n
        dedup_k = 0
        if self.dedup and lines_p is not None and n:
            # Longest line in this batch (line ids are non-decreasing):
            # when it fits the window, the cheap shifted-compare dedup
            # variant is exact; wider lines take the sort variant.
            la = np.asarray(lines_p[:n])
            bound = np.flatnonzero(np.diff(la)) + 1
            runs = np.diff(np.concatenate(([0], bound, [n])))
            if int(runs.max()) <= _DEDUP_WINDOW:
                dedup_k = _DEDUP_WINDOW
        fn = _table_program(npad, L, self.cap, self.Lcap, self.dedup,
                            _acc_dtype().name, dedup_k)
        nbytes = mat.nbytes + lens_p.nbytes + lines_p.nbytes
        if self.store is not None:
            self.store.count_h2d(nbytes)
        with devtime.track("device"), _trace.span(
                "handoff", "table-probe", tokens=int(n),
                bytes=int(nbytes)):
            self.acc, miss, n_miss = fn(
                jnp.asarray(mat), jnp.asarray(lens_p),
                jnp.asarray(lines_p), self.tab_h1, self.tab_slot,
                self.tab_mat, self.tab_lens, self.acc)
        self.table_batches += 1
        return _TableBatch(miss, n_miss, starts, lens, lines, n, npad)

    def drain(self, buf, batch):
        """Resolve one table dispatch: fetch the (tiny) miss evidence,
        absorb misses exactly on the host, and credit the drain bytes the
        classic program would have fetched.  Returns (ok, miss_frac);
        ``ok=False`` means NO miss count landed (the absorb is
        transactional: slots inserted before the refusal carry zero
        counts, which the degrade flush drops) — the caller must emit
        ``batch.miss_idx``'s tokens through the exact host path or they
        are lost."""
        n_miss = int(batch.n_miss)
        fetched = 4
        ok = True
        if n_miss:
            miss = np.asarray(batch.miss)[:batch.n]
            fetched += batch.npad  # the bool lane
            idx = np.flatnonzero(miss)
            batch.miss_idx = idx
            ok = self._absorb_miss_tokens(
                buf, batch.starts[idx], batch.lens[idx],
                batch.lines[idx] if batch.lines is not None else None)
        if self.store is not None:
            self.store.count_d2h(fetched)
            if ok:
                # Only a batch that stayed on the tier claims the
                # avoided drain (a degrading batch is leaving it).
                self.store.count_d2h_avoided(
                    max(0, CLASSIC_DRAIN_BYTES_PER_SLOT * batch.npad
                        - fetched))
        return ok, (n_miss / float(batch.n) if batch.n else 0.0)

    def _absorb_miss_tokens(self, buf, starts, lens, lines):
        """Exact host grouping of a batch's missed tokens
        (:func:`group_token_rows` — the same grouping the classic host
        fallback uses), then slot insert + scatter."""
        if not len(starts):
            return True
        uniq, counts = group_token_rows(
            buf, starts, lens, lines,
            self.dedup and lines is not None)
        raws = [None] * len(uniq)
        for i in range(len(uniq)):
            ln = int(uniq[i, 0])
            raws[i] = uniq[i, 1:1 + ln].tobytes()
        slots = self.lookup_or_insert(raws)
        if slots is None:
            return False
        return self.scatter_counts(slots, counts)

    # -- endgame -----------------------------------------------------------
    def flush_block(self):
        """Degrade: one d2h of the accumulator -> a hash-sorted host
        block, byte-identical to what the classic combine would have
        produced; the job continues on the spill path."""
        if self.nslots == 0:
            self._reset()
            return None
        counts = np.asarray(self.acc[:self.nslots]).astype(np.int64)
        if self.store is not None:
            # Charge what actually crossed the boundary: the
            # accumulator's own lane width, not the int64-widened copy.
            self.store.count_d2h(self.nslots * _acc_dtype().itemsize)
        from ..blocks import Block

        keys = np.empty(self.nslots, dtype=object)
        for i, k in enumerate(self.keys):
            keys[i] = k
        h1 = np.asarray(self.h1, dtype=np.uint32)
        h2 = np.asarray(self.h2, dtype=np.uint32)
        keep = counts > 0
        blk = Block(keys[keep], counts[keep], h1[keep], h2[keep])
        self._reset()
        if not len(blk):
            return None
        return blk.sort_by_hash()

    def degrade(self, reason):
        self.degraded = True
        self.degrade_reason = reason
        if self.store is not None:
            self.store.count_handoff_degrade()
        _trace.instant("handoff", "degrade", reason=reason)
        log.info("handoff degraded to the spill path: %s", reason)
        return self.flush_block()

    def _reset(self):
        self.acc = None
        self.tab_h1 = self.tab_slot = None
        self.tab_mat = self.tab_lens = None
        self.cap = 0
        self.nslots = 0
        self.bytes2slot = {}
        self.keys = []
        self.slot_bytes = []
        self.h1 = []
        self.h2 = []
        self._pending_rows = []
        self._tab_dirty = True
        self._lanes_forced = False
        self._lanes_deferred = 0
        self.table_mode = False

    def finalize(self, store, n_partitions):
        """Job end: the accumulator becomes per-partition HBM-resident
        refs — hash-sorted within each partition, exactly the layout the
        classic combine would have registered — registered under the
        store's budget/attempt discipline.  Returns ``(blocks, {pid:
        [BlockRef]})``: at most one side is non-empty (``blocks`` is the
        degrade flush the caller must push through the classic path)."""
        import jax
        import jax.numpy as jnp

        from ..storage import BlockRef

        if self.degraded or self.nslots == 0:
            self._reset()
            return (), {}
        if self.device_bytes() + self.nslots * 16 > self.budget:
            blk = self.degrade("hbm budget exceeded at finalize")
            return ((blk,) if blk is not None else ()), {}
        h1 = np.asarray(self.h1, dtype=np.uint32)
        h2 = np.asarray(self.h2, dtype=np.uint32)
        order = np.lexsort((h2, h1))
        pid = (h1[order] % np.uint32(n_partitions)).astype(np.int32)
        porder = np.argsort(pid, kind="stable")
        perm = order[porder]
        sorted_pid = pid[porder]
        keys = np.empty(self.nslots, dtype=object)
        for i, k in enumerate(self.keys):
            keys[i] = k
        with devtime.track("device"), _trace.span(
                "handoff", "finalize", records=int(self.nslots)):
            perm_dev = jnp.asarray(perm.astype(np.int32))
            vals = jnp.take(self.acc, perm_dev)
            mins = jnp.min(vals) if self.nslots else None
        if self.store is not None:
            self.store.count_h2d(perm.nbytes)
        bounds = np.flatnonzero(np.diff(sorted_pid)) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [self.nslots]))
        lane_min = int(mins) if mins is not None else 0
        mapping = {}
        total_dev = 0
        for s, e in zip(starts, ends):
            p = int(sorted_pid[s])
            seg = perm[s:e]
            dev_v = vals[int(s):int(e)]
            h1_seg = h1[seg]
            h2_seg = h2[seg]
            dev_h1 = jax.device_put(h1_seg)
            dev_h2 = jax.device_put(h2_seg)
            # total_added is guarded under the lane bound, so the segment
            # sum is exact in the accumulator dtype.
            lane_abs = int(jnp.sum(dev_v))
            ref = BlockRef.from_device_lanes(
                keys.take(seg), h1_seg, h2_seg, dev_v, dev_h1, dev_h2,
                store=store, value_dtype=np.int64, lane_abs=lane_abs,
                lane_min=lane_min,
                h2d_bytes=h1_seg.nbytes + h2_seg.nbytes)
            store.register_device(ref)
            total_dev += ref.dev_bytes
            mapping.setdefault(p, []).append(ref)
        _trace.instant("handoff", "registered", bytes=int(total_dev),
                       partitions=len(mapping))
        self._reset()
        return (), mapping
