"""Sort-based grouping and segment reduction.

This is the TPU-native replacement for the reference's external-sort grouping
machinery: sorted spill runs + k-way heap merge + itertools.groupby (reference
dampr/dataset.py:161-188, 567-588; base.py:184-195 ``yield_groups``).  Instead of
comparison-sorting Python objects, we:

1. lexsort the dual hash lanes ``(h1, h2)`` — ``lax.sort(num_keys=…)`` on device,
   ``np.lexsort`` on host for small blocks;
2. find segment boundaries by adjacent-hash inequality;
3. fold numeric values with ``jax.ops.segment_sum``-family kernels, or yield
   per-group Python lists for opaque reducers.

Exactness: after sorting we verify that adjacent records with equal hashes have
equal *real* keys (vectorized compare).  On the (astronomically rare) 64-bit
collision the affected block falls back to exact host grouping by real key.
"""

import functools

import numpy as np

from .. import settings

# ---------------------------------------------------------------------------
# Associative fold descriptors (DSL-recognized ops that fold on device)
# ---------------------------------------------------------------------------


class AssocOp(object):
    """Descriptor for an associative binop.  ``kind`` is a device-foldable tag
    ('sum'|'min'|'max') or None for opaque Python binops (host dict combine).
    ``fn`` is the Python binop used for host fallback and object values.
    ``elementwise`` marks ops whose fn IS elementwise over tuple/composite
    values, so 2D lanes may fold vectorized — a plain ``min`` over tuples
    is lexicographic, NOT elementwise, and must stay on the fn path."""

    __slots__ = ("kind", "fn", "elementwise")

    def __init__(self, kind, fn, elementwise=False):
        self.kind = kind
        self.fn = fn
        self.elementwise = elementwise

    def __call__(self, a, b):
        return self.fn(a, b)


SUM = AssocOp("sum", lambda a, b: a + b)
MIN = AssocOp("min", lambda a, b: a if a <= b else b)
MAX = AssocOp("max", lambda a, b: a if a >= b else b)
FIRST = AssocOp("first", lambda a, _b: a)
#: Elementwise pair sum: composite (sum, count)-style accumulators.  The
#: "sum" kind rides the vectorized 2D-lane segment kernels; the fn gives
#: object-lane tuples an exact pairwise fold (plain SUM.fn would
#: CONCATENATE tuples).
PAIR_SUM = AssocOp("sum", lambda a, b: (a[0] + b[0], a[1] + b[1]),
                   elementwise=True)


def _builtin_ops():
    import operator
    return {operator.add: SUM, operator.iadd: SUM,
            min: MIN, max: MAX}


_BUILTIN_OPS = None


def as_assoc_op(binop):
    """Wrap a Python binop; recognized builtins (operator.add, min, max) get a
    device-foldable kind so ``count()``/``a_group_by(...).reduce(operator.add)``
    hit segment kernels, not per-record Python."""
    global _BUILTIN_OPS
    if isinstance(binop, AssocOp):
        return binop
    if _BUILTIN_OPS is None:
        _BUILTIN_OPS = _builtin_ops()
    hit = _BUILTIN_OPS.get(binop)
    if hit is not None:
        return hit
    return AssocOp(None, binop)


# ---------------------------------------------------------------------------
# Hash lexsort
# ---------------------------------------------------------------------------


def _pow2(n):
    return max(8, 1 << max(0, (n - 1).bit_length()))


@functools.lru_cache(maxsize=None)
def _lexsort_jit():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def kernel(valid, h1, h2):
        iota = jnp.arange(h1.shape[0], dtype=jnp.int32)
        _, sh1, sh2, perm = lax.sort((valid, h1, h2, iota), num_keys=3,
                                     is_stable=True)
        return sh1, sh2, perm

    return jax.jit(kernel)


def hash_sort_perm(h1, h2):
    """Return the stable permutation sorting records by (h1, h2)."""
    n = len(h1)
    if settings.use_device_for(n):
        from . import devtime

        npad = _pow2(n)
        valid = np.zeros(npad, dtype=np.uint8)
        if npad != n:
            valid[n:] = 1
            h1 = np.pad(h1, (0, npad - n))
            h2 = np.pad(h2, (0, npad - n))
        with devtime.track("device"):
            _, _, perm = _lexsort_jit()(valid, h1, h2)
            return np.asarray(perm)[:n]
    return np.lexsort((h2, h1)).astype(np.int32)


# ---------------------------------------------------------------------------
# Grouping
# ---------------------------------------------------------------------------


def _adjacent_new_segment(h1s, h2s):
    """Boolean[n]: True where a new (h1,h2) segment starts (position 0 inclusive)."""
    n = len(h1s)
    starts = np.empty(n, dtype=bool)
    if n == 0:
        return starts
    starts[0] = True
    np.not_equal(h1s[1:], h1s[:-1], out=starts[1:])
    starts[1:] |= h2s[1:] != h2s[:-1]
    return starts


def _keys_adjacent_equal(keys_sorted):
    """Boolean[n-1]: keys_sorted[i] == keys_sorted[i+1], vectorized where possible."""
    if keys_sorted.dtype != object:
        return keys_sorted[1:] == keys_sorted[:-1]
    eq = np.empty(len(keys_sorted) - 1, dtype=bool)
    a = keys_sorted[:-1]
    b = keys_sorted[1:]
    for i in range(len(eq)):
        eq[i] = a[i] == b[i]
    return eq


class SortedGroups(object):
    """A block sorted by hash with verified exact segment boundaries.

    ``starts`` indexes the first record of each group; ``block`` is the sorted
    block; groups are contiguous slices.  Construction detects hash collisions and
    repairs boundaries so every segment holds exactly one distinct key.
    """

    __slots__ = ("block", "starts")

    def __init__(self, block, starts):
        self.block = block
        self.starts = starts

    @property
    def n_groups(self):
        return len(self.starts)

    def group_keys(self):
        return self.block.keys.take(self.starts)

    def bounds(self):
        ends = np.empty_like(self.starts)
        ends[:-1] = self.starts[1:]
        if len(ends):
            ends[-1] = len(self.block)
        return self.starts, ends

    def iter_groups(self):
        """Yield (key, [values]) per group — values materialized as a list,
        mirroring the reference's grouped_read (dataset.py:429-433)."""
        from ..blocks import pylist

        starts, ends = self.bounds()
        keys = self.block.keys
        vals = self.block.values
        for i in range(len(starts)):
            k = keys[starts[i]]
            yield (
                k.item() if isinstance(k, np.generic) else k,
                pylist(vals[starts[i]: ends[i]]),
            )


def sort_and_group(block):
    """Sort a Block by hash and return exact SortedGroups."""
    from ..blocks import Block

    n = len(block)
    if n == 0:
        return SortedGroups(block, np.empty(0, dtype=np.int64))
    h1, h2 = block.hashes()
    perm = hash_sort_perm(h1, h2)
    sb = block.take(perm)
    starts_mask = _adjacent_new_segment(sb.h1, sb.h2)

    # Collision / exactness check: same-hash neighbors must hold equal keys.
    same_hash = ~starts_mask[1:]
    if same_hash.any():
        keq = _keys_adjacent_equal(sb.keys)
        bad = same_hash & ~keq
        if bad.any():
            # Rare path: refine boundaries by real key within colliding runs.
            starts_mask[1:] |= bad
            # Note: records of the colliding keys may interleave within the
            # hash-run; enforce exact grouping by stable-subsorting the run.
            starts_mask = _repair_collisions(sb, starts_mask)
    return SortedGroups(sb, np.flatnonzero(starts_mask))


def _repair_collisions(sb, starts_mask):
    """Exact regroup of hash-runs that contain >1 distinct key.  Reorders records
    inside each colliding run so equal keys are contiguous, and rebuilds the
    starts mask.  O(run length) Python — runs are tiny and collisions rare."""
    h1, h2 = sb.h1, sb.h2
    run_starts = np.flatnonzero(_adjacent_new_segment(h1, h2))
    run_ends = np.append(run_starts[1:], len(sb))
    perm = np.arange(len(sb))
    new_mask = starts_mask.copy()
    for s, e in zip(run_starts, run_ends):
        if e - s <= 1:
            continue
        seg = sb.keys[s:e]
        distinct = {}
        multi = False
        for i in range(len(seg)):
            kk = seg[i]
            found = None
            for did, (dk, idxs) in distinct.items():
                if dk == kk:
                    found = did
                    break
            if found is None:
                distinct[len(distinct)] = (kk, [i])
            else:
                distinct[found][1].append(i)
        if len(distinct) > 1:
            multi = True
        if multi:
            order = []
            starts_local = []
            for _, (dk, idxs) in distinct.items():
                starts_local.append(len(order))
                order.extend(idxs)
            perm[s:e] = s + np.asarray(order)
            new_mask[s:e] = False
            for sl in starts_local:
                new_mask[s + sl] = True
    # apply permutation in place
    sb.keys = sb.keys.take(perm)
    sb.values = sb.values[perm]
    sb.h1 = sb.h1.take(perm)
    sb.h2 = sb.h2.take(perm)
    return new_mask


# ---------------------------------------------------------------------------
# Segment folds
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _segment_fold_jit(kind, num_segments):
    import jax
    import jax.numpy as jnp

    def kernel(vals, seg_ids):
        if kind == "sum":
            return jax.ops.segment_sum(vals, seg_ids, num_segments=num_segments)
        if kind == "min":
            return jax.ops.segment_min(vals, seg_ids, num_segments=num_segments)
        if kind == "max":
            return jax.ops.segment_max(vals, seg_ids, num_segments=num_segments)
        raise ValueError(kind)

    return jax.jit(kernel)


_NP_FOLD = {
    "sum": np.add,
    "min": np.minimum,
    "max": np.maximum,
}

_I32_MAX = 2 ** 31 - 1
_I64_MAX = 2 ** 63 - 1


def _device_fold_exact(vals, kind):
    """True when folding ``vals`` in the device's 32-bit lanes is exact
    (jax_enable_x64 is off, so int64/float64 inputs would silently truncate
    to int32/float32 on device — the host numpy path stays exact instead).

    - int64: every *result* must fit int32; for 'sum' bound by sum(|v|)
      (conservative: any per-group sum is within it), for min/max by max(|v|).
    - float64: device would drop to float32 precision; keep on host unless
      values already are float32.
    """
    import jax

    if vals.dtype == object:
        return False  # promoted-to-object exact host fold (huge uint64 sums)
    if jax.config.jax_enable_x64:
        return True
    if vals.dtype == np.uint64:
        return False  # 32-bit lanes truncate; host uint64 min/max is exact
    if vals.dtype == np.int64:
        if not len(vals):
            return True
        lo, hi = int(vals.min()), int(vals.max())
        if lo < -_I32_MAX - 1 or hi > _I32_MAX:
            return False  # (min/max never overflow; np.abs would wrap at int64 min)
        if kind == "sum":
            # |v| <= 2**31 each, so the int64 abs-sum is exact for any
            # realistic block length; it bounds every per-group sum.
            return int(np.abs(vals).sum()) <= _I32_MAX
        return True
    if vals.dtype == np.float64:
        return False
    return True


def fold_sorted(groups, op):
    """Fold each group's values with ``op`` -> compacted Block (one record per
    group, hashes preserved).  Device segment kernels when ``op.kind`` is
    recognized and values are numeric; host otherwise."""
    from ..blocks import Block, _column_from_list

    sb = groups.block
    starts, ends = groups.bounds()
    n = len(sb)
    ng = groups.n_groups
    if ng == 0:
        return Block.empty()

    kh1 = sb.h1.take(starts)
    kh2 = sb.h2.take(starts)
    keys = sb.keys.take(starts)

    if op.kind == "first":
        # Stable sort preserves arrival order within groups, so the group's
        # first record is at its start offset — a pure gather, any dtype.
        return Block(keys, sb.values[starts], kh1, kh2)

    if (op.kind in _NP_FOLD and sb.numeric_values
            and (sb.values.ndim == 1 or op.elementwise)):
        # 2D composite lanes only fold vectorized for ops declaring
        # elementwise tuple semantics (PAIR_SUM); a generic min/max/add
        # over tuples means lexicographic-compare / concatenation and
        # takes the fn path below.
        vals = sb.values
        if vals.dtype == np.bool_:
            # Python semantics: True + True == 2; promote before folding
            # (min/max could stay bool, but a uniform int64 lane is simpler and
            # round-trips bools as 0/1 exactly like the reference's binop).
            vals = vals.astype(np.int64)
        elif vals.dtype == np.uint64 and op.kind == "sum":
            # uint64 sums wrap silently in numpy's host reduceat; when even
            # the conservative whole-array bound (n * max) fits int64 the
            # checked int64 path is exact, otherwise fold as Python ints.
            # min/max stay native uint64 — reduceat compares exactly there,
            # and _device_fold_exact keeps uint64 off the 32-bit lanes.
            if not len(vals) or len(vals) * int(vals.max()) <= _I64_MAX:
                vals = vals.astype(np.int64)
            else:
                ov = np.empty(len(vals), dtype=object)
                ov[:] = [int(x) for x in vals]
                vals = ov
        elif (op.kind == "sum" and vals.dtype.kind in "iu"
                and vals.dtype.itemsize < 8):
            # Narrow int sums wrap silently in both reduceat and the 32-bit
            # device lanes; the reference folds in arbitrary-precision Python
            # ints, so promote to int64 (then the int64 exactness check below
            # governs device eligibility as usual).
            vals = vals.astype(np.int64)
        if (settings.use_device_for(n)
                and _device_fold_exact(vals, op.kind)):
            # Segment ids must come from the collision-repaired group bounds,
            # not raw (h1,h2) adjacency — after a 64-bit collision the repaired
            # starts split a hash-run into multiple real-key groups.
            import jax as _jax
            if not _jax.config.jax_enable_x64:
                # Explicit lossless cast into the 32-bit device lanes
                # (_device_fold_exact guaranteed representability).
                if vals.dtype == np.int64:
                    vals = vals.astype(np.int32)
            from . import devtime
            seg_ids = np.repeat(np.arange(ng, dtype=np.int64), ends - starts)
            npad = _pow2(n)
            ng_pad = _pow2(ng)
            if npad != n:
                pad_val = {"sum": 0, "min": vals.dtype.type(np.inf) if vals.dtype.kind == "f" else np.iinfo(vals.dtype).max,
                           "max": vals.dtype.type(-np.inf) if vals.dtype.kind == "f" else np.iinfo(vals.dtype).min}[op.kind]
                pad_spec = ((0, npad - n), (0, 0)) if vals.ndim == 2 else (0, npad - n)
                vals = np.pad(vals, pad_spec, constant_values=pad_val)
                seg_ids = np.pad(seg_ids, (0, npad - n), constant_values=ng_pad - 1)
            with devtime.track("device"):
                folded = np.asarray(
                    _segment_fold_jit(op.kind, ng_pad)(vals, seg_ids.astype(np.int32)))[:ng]
            # padding contributed only to the last (possibly real) segment when
            # ng == ng_pad and op == sum with pad 0 / min with inf — safe by
            # construction of pad values going to segment ng_pad-1 only if
            # ng < ng_pad; otherwise pad rows land in the real last segment with
            # identity pad values, which is still correct.
        else:
            ufunc = _NP_FOLD[op.kind]
            folded = ufunc.reduceat(vals, starts)
        return Block(keys, folded, kh1, kh2)

    # Host generic fold.  C-level lane conversion (pylist unboxes numpy
    # scalars, so a user binop never sees an np.int64 that would wrap
    # silently) happens in bounded windows — whole-lane boxing would
    # multiply the footprint of near-budget partitions, the same
    # discipline Block.iter_pairs applies.
    from ..blocks import pylist

    W = 65536
    out_vals = [None] * ng
    fn = op.fn
    varr = sb.values
    gi = 0
    while gi < ng:
        s0 = int(starts[gi])
        e0 = int(ends[gi])
        if e0 - s0 > W:
            # One oversized group: fold it across bounded boxed windows,
            # carrying the accumulator.
            acc = None
            first = True
            for w0 in range(s0, e0, W):
                it = iter(pylist(varr[w0:min(e0, w0 + W)]))
                if first:
                    acc = next(it)
                    first = False
                for v in it:
                    acc = fn(acc, v)
            out_vals[gi] = acc
            gi += 1
            continue
        # A run of whole groups fitting one window: one conversion, tight
        # per-group loops over local offsets.
        ge = gi + 1
        while ge < ng and int(ends[ge]) - s0 <= W:
            ge += 1
        win = pylist(varr[s0:int(ends[ge - 1])])
        ls = (starts[gi:ge] - s0).tolist()
        le = (ends[gi:ge] - s0).tolist()
        for i in range(ge - gi):
            acc = win[ls[i]]
            for j in range(ls[i] + 1, le[i]):
                acc = fn(acc, win[j])
            out_vals[gi + i] = acc
        gi = ge
    return Block(keys, _column_from_list(out_vals), kh1, kh2)


def fold_block(block, op):
    """sort_and_group + fold_sorted in one call (map-side combine compaction)."""
    return fold_sorted(sort_and_group(block), op)
