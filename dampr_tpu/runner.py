"""Stage executor: walks the logical graph and runs each stage.

Replaces the reference's fork-join machinery (dampr/runner.py:137-374 +
stagerunner.py) with a thread-pool executor over columnar block jobs:

- **Map stages** stream records through the fused mapper chain into blocks;
  associative stages fold map-side (the ``PartialReduceCombiner`` +
  ``ReducedWriter`` path, reference stagerunner.py:79-129) via vectorized
  segment kernels; every map output is hash-partitioned into the run's
  ``n_partitions`` (the reference's ``DefaultShuffler``, base.py:416-433).
- **Reduce stages** build a key-sorted :class:`~dampr_tpu.base.GroupedView`
  per (partition, input) — vectorized hash-sort replacing sorted-spill +
  heapq merge — and stream the reducer's output back into blocks.
- **Sink stages** write durable part-files exempt from cleanup.

Threads (not forked processes) carry the jobs: the heavy keyed work happens in
numpy/XLA kernels that release the GIL, and a single process keeps one device
context (forking around a live TPU runtime is not safe).  Stage barriers are
preserved: stage N completes before N+1 starts, exactly like the reference's
per-stage join (runner.py:174-232).

Failure semantics: a job exception fails the run immediately with the original
traceback (the reference deadlocks on a dead worker — stagerunner.py:35-38 —
which SURVEY.md flags as a defect not to replicate).
"""

import copy
import itertools
import logging
import os
import queue as _queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from . import base, faults as _faults, settings, storage
from .parallel import mitigate as _mitigate
from .blocks import Block, BlockBuilder
from .dataset import BlockDataset, Chunker, Dataset, SinkDataset
from .graph import GInput, GMap, GReduce, GSink
from .obs import log as _obslog
from .obs import metrics as _metrics
from .obs import profile as _profile
from .obs import trace as _trace
from .ops import segment

log = logging.getLogger("dampr_tpu.runner")

# Cap on accumulated map-side partial folds before re-compaction; bounds the
# map-side working set the way the reference's reduce_buffer flush does
# (dampr.py:661-673) but in block units.
_PARTIAL_FANIN = 8

#: Device-partial compaction trigger for the mesh fold (lanes, not
#: partial count): vocabulary-sized handoff partials accumulate until one
#: deterministic refold, while capacity-sized window partials still
#: compact before they stack past device memory.
_REFOLD_LANE_CAP = 1 << 20


def _clone_op(op):
    """Per-job operator instance.  The built-in stateless wrapper ops
    (Map/RecordOps/StreamMapper/Reduce/joins/…) define ``__deepcopy__`` as
    share-by-reference (base._shared_instance_deepcopy), so user callables —
    which may hold uncopyable resources — are never descended into.
    Everything else still deep-copies: BlockMapper/BlockReducer lifecycle
    ops (per-chunk state the reference isolated by process fork) and
    unknown user Mapper/Reducer subclasses installed via custom_mapper /
    custom_reducer, which may be stateful — such a subclass holding an
    uncopyable resource should define ``__deepcopy__`` itself.  deepcopy of
    a fused Composed chain reaches the stateful leaves while sharing the
    rest."""
    return copy.deepcopy(op)


class _SinkOutput(object):
    """Durable sink result: a list of part-file datasets."""

    def __init__(self, paths):
        self.paths = paths

    def datasets(self):
        return [SinkDataset(p) for p in self.paths]


def _exchange_mesh_gate(budget, target=None):
    """Shared engage/window policy for every mesh byte-exchange user.
    Returns (mesh, D, window_bytes) or None when the path is off or only
    one device is visible.  The window bound keeps the host-side pack
    working set a fraction of the run budget (the DEVICE-side bound is
    separate: the exchange itself runs a replan schedule under
    ``settings.exchange_hbm_budget``).

    ``target`` is the plan layer's per-stage shuffle choice
    (``"mesh"``/``"host"``, from the cost model over the run-history
    corpus): ``"host"`` declines the mesh path in auto mode, ``"mesh"``
    engages it even where the auto device-count heuristic would not.
    Explicit ``settings.mesh_exchange`` modes always win — the plan's
    choice was made under the same mode, so only auto runs ever diverge."""
    mode = str(settings.mesh_exchange).lower()
    if mode in ("off", "0", "false") or not settings.use_device:
        return None
    if mode not in ("on", "1", "true"):
        if target == "host":
            return None
        if target != "mesh" and settings.device_count_for_auto() < 2:
            return None
    from .parallel.mesh import data_mesh, mesh_size

    mesh = data_mesh()
    D = mesh_size(mesh)
    window = max(1 << 18, budget // (8 * D * D))
    return mesh, D, window


def _overlap_stream(items, store, size_of=None):
    """The stage-overlapped streaming executor's pipe: run ``items`` — the
    codec, a generator whose ``next()`` does the decompress/tokenize/parse
    work — on a dedicated producer thread that stays up to
    ``settings.overlap_windows`` produced blocks ahead of the consumer (the
    fold/register loop on the job thread).  This extends the readahead
    pattern ``inputs.Readahead`` applies to raw chunk bytes up through the
    codec: while the current block folds, the next window is already being
    tokenized.

    Memory discipline: every in-flight block is charged byte-for-byte
    against the run's budget (``store.reserve_overlap``) from the moment
    the codec emits it until the consumer has finished folding it, so
    readahead displaces resident refs (they spill) instead of stacking on
    top of the stage ceiling.  The charge is released in a ``finally`` on
    both sides — consumer abandonment (a failed fold mid-window, a retried
    job) stops the producer and drains every outstanding reservation, so a
    killed window can never leak budget.

    Critical-path accounting: while this consumer blocks on the queue
    with its producer inside the native codec, the slot is marked
    stalled (devtime.slot_stall); the ``codec_wait`` bucket accumulates
    the WALL-CLOCK union of intervals where every live slot is stalled
    at once — the codec seconds no fold anywhere could cover, i.e. the
    codec time still on the engine's critical path after overlapping.
    (With overlap off the job thread runs the codec itself, so the whole
    ``codec`` bucket is non-overlapped by construction.)

    Returns ``items`` unchanged when overlap is disabled or there is no
    store to account against."""
    depth = settings.overlap_windows
    if depth <= 0 or store is None:
        return items
    if size_of is None:
        size_of = lambda b: b.nbytes()  # noqa: E731
    from .ops import devtime

    # Each produced window records one codec span on the producer thread's
    # lane (a no-op pass-through when tracing is off).  The span covers the
    # generator's next() — decompress + tokenize/parse — not the queue wait.
    items = _trace.timed_iter(items, "codec", "codec-window")

    q = _queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    state = {"err": None, "done": False}
    _END = object()

    def produce():
        try:
            for item in items:
                # Fault site: chaos tests widen the producer/consumer
                # shutdown race here (sleep action) to prove reservation
                # accounting survives a consumer that dies mid-run.
                _faults.check("overlap_produce")
                if stop.is_set():
                    return
                if item is None:
                    # The serial consumer drops None windows (a
                    # map_blocks mapper may emit them for empty input);
                    # keep that contract rather than crash size_of.
                    continue
                nb = size_of(item) or 0
                _metrics.counter_add("overlap.windows", 1)
                if nb:
                    store.reserve_overlap(nb)
                placed = False
                while not stop.is_set():
                    try:
                        q.put((item, nb), timeout=0.05)
                        placed = True
                        break
                    except _queue.Full:
                        continue
                if not placed:
                    if nb:
                        store.release_overlap(nb)
                    return
        except BaseException as e:  # delivered to the consumer
            state["err"] = e
        finally:
            state["done"] = True
            while not stop.is_set():
                try:
                    q.put((_END, 0), timeout=0.05)
                    break
                except _queue.Full:
                    continue

    thread = threading.Thread(target=produce, daemon=True,
                              name="dampr-tpu-codec")

    def gen():
        thread.start()
        devtime.slot_enter()
        try:
            while True:
                # Stall accounting per poll slice: the slot counts as
                # blocked-on-codec only while THIS job's producer thread
                # is executing the native codec (devtime.active_in) —
                # wait caused by producer-side IO/Python is real pipeline
                # wait but is not codec-attributable (the ``codec``
                # bucket doesn't count it either), and a sibling job's
                # codec is not what this fold is blocked on.
                wait_t0 = 0.0
                while True:
                    try:
                        item, nb = q.get_nowait()
                        break
                    except _queue.Empty:
                        pass
                    if not wait_t0:
                        wait_t0 = _trace.now()
                    stalled = devtime.active_in(thread.ident, "codec")
                    if stalled:
                        devtime.slot_stall()
                    try:
                        item, nb = q.get(timeout=0.05)
                        got = True
                    except _queue.Empty:
                        got = False
                    finally:
                        if stalled:
                            devtime.slot_unstall()
                    if got:
                        break
                    if state["done"] and q.empty():
                        item, nb = _END, 0
                        break
                if wait_t0:
                    # Consumer-side pipeline wait (this slot's fold was
                    # blocked on its producer) — the per-slot view of what
                    # devtime's codec_wait aggregates across all slots.
                    _trace.complete("stall", "pipe-wait", wait_t0)
                    _metrics.counter_add("overlap.consumer_stalls", 1)
                if item is _END:
                    if state["err"] is not None:
                        raise state["err"]
                    return
                try:
                    yield item
                finally:
                    if nb:
                        store.release_overlap(nb)
        finally:
            devtime.slot_exit()
            stop.set()

            def drain():
                while True:
                    try:
                        _item, nb = q.get_nowait()
                    except _queue.Empty:
                        return
                    if nb:
                        store.release_overlap(nb)

            drain()
            thread.join(timeout=5.0)
            if thread.is_alive():
                # A producer stuck inside the native codec (or a wedged
                # disk under it) past the join deadline: name it loudly
                # instead of silently abandoning the join result, and
                # keep draining briefly — the producer releases its own
                # reservation when it observes ``stop``, but an item it
                # slips into the queue after our drain would otherwise
                # leak its budget charge until process exit.
                _obslog.warn(
                    "overlap-producer-stuck",
                    "overlap producer thread %s did not stop within "
                    "5.0s at shutdown; draining in-flight windows in "
                    "the background (daemon thread abandoned)",
                    thread.name, logger=log, thread=thread.name)
                deadline = time.perf_counter() + 5.0
                while thread.is_alive() and time.perf_counter() < deadline:
                    drain()
                    thread.join(timeout=0.05)
                if thread.is_alive():
                    _obslog.warn(
                        "overlap-producer-stuck",
                        "overlap producer thread %s still alive after "
                        "drain grace; any window it produces past this "
                        "point leaks its budget reservation until the "
                        "store is cleaned up", thread.name, logger=log,
                        thread=thread.name, after_drain=True)
            # The producer may have slipped one reserved block into the
            # slot the first drain freed before it observed ``stop`` —
            # with the thread joined (or the grace above spent), a final
            # drain is conclusive.
            drain()

    return gen()


class _FoldDeclined(Exception):
    """Internal _StreamFolder control flow: this mapping keeps its
    original refs (ineligible dtype or mid-drain disable)."""


class _StreamFolder(object):
    """Consumer half of a streamed map->keyed-fold edge (docs/pipeline.md):
    completed map-job partition mappings publish into a bounded queue and
    a folder thread pre-folds each one under the consuming reduce's
    associative op while the map stage is still running, so the reduce
    inherits compacted partials and the fold work hides under map compute.

    Byte-identity contract: folding only regroups partials across jobs —
    both reduce paths fold the exact hash groups and emit in ascending
    real-key order, so for commutative ops the regrouping cannot change a
    single output byte.  Commutativity is gated per block at run time
    (the coded-exchange exactness rule): ``sum`` folds integer/bool value
    lanes only (reordered float addition is not byte-identical), min/max
    fold any numeric lane.  The first ineligible block disables folding
    for the stage — remaining mappings pass through untouched, which is
    always correct.

    Backpressure: ``publish`` runs on the dispatching thread AFTER the
    job's result committed (attempt rollback and speculation already
    resolved) and blocks while queued bytes exceed ``bound``.  Queued
    bytes are charged through ``store.reserve_overlap`` so spill
    admission sees the pressure; the charge releases as each mapping
    folds.  A folder error never fails the run — the affected mappings
    keep their original refs."""

    def __init__(self, store, op, bound, device=False, label="early-fold"):
        self.store = store
        self.op = op
        self.bound = max(1, int(bound))
        self.device = device
        self.label = label
        self.folded = {}    # job idx -> replacement mapping
        self.fold_delta = {}  # pid -> staged-bytes minus folded-bytes
        self.stats = {"published": 0, "early_folded_blocks": 0,
                      "bytes_in": 0, "bytes_out": 0, "fold_seconds": 0.0,
                      "overlap_seconds": 0.0, "stall_seconds": 0.0,
                      "queue_peak_bytes": 0, "queue_depth_series": []}
        self._q = _queue.Queue()
        self._cv = threading.Condition()
        self._pending = 0
        self._disabled = False
        self._t0 = time.perf_counter()
        self._pool_done_at = None
        self._thread = threading.Thread(
            target=self._run, name="dampr-tpu-pipefold", daemon=True)
        self._thread.start()

    def _sample_depth(self):
        # Bounded queue-depth series for stats()["pipeline"]: decimate by
        # dropping every other sample once the cap is hit, so the series
        # stays an even sketch of the whole stage.
        series = self.stats["queue_depth_series"]
        series.append([round(time.perf_counter() - self._t0, 4),
                       self._pending])
        if len(series) > 512:
            del series[::2]

    def publish(self, idx, mapping):
        """Dispatch-thread side: charge, bound, enqueue.  ``mapping`` is
        the committed job result ({pid: [refs]}); the folder may replace
        it wholesale in ``self.folded[idx]``."""
        _faults.check("stream_publish")
        if self._disabled:
            return
        nb = sum(ref.total_bytes for refs in mapping.values()
                 for ref in refs)
        if nb <= 0:
            return
        wait_t0 = 0.0
        with self._cv:
            while (self._pending > 0 and self._pending + nb > self.bound
                    and not self._disabled):
                if not wait_t0:
                    wait_t0 = _trace.now()
                self._cv.wait(0.05)
            if self._disabled:
                if wait_t0:
                    self.stats["stall_seconds"] += _trace.now() - wait_t0
                return
            self._pending += nb
            self.stats["queue_peak_bytes"] = max(
                self.stats["queue_peak_bytes"], self._pending)
            self._sample_depth()
        if wait_t0:
            self.stats["stall_seconds"] += _trace.now() - wait_t0
            # "stream-wait" (not the overlap executor's "pipe-wait"):
            # critpath classifies this name as pipeline-stall, whose
            # doctor fix (raise pipeline_queue_bytes) differs from the
            # overlap knobs.
            _trace.complete("stall", "stream-wait", wait_t0)
        self.store.reserve_overlap(nb)
        self.stats["published"] += 1
        self.stats["bytes_in"] += nb
        self._q.put((idx, mapping, nb))

    def _value_dtype_ok(self, block):
        dt = getattr(getattr(block, "values", None), "dtype", None)
        if dt is None:
            return False
        if self.op.kind == "sum":
            return dt.kind in "iub"
        return dt.kind in "iubf"

    def _fold_one(self, idx, mapping):
        """Fold one job mapping, atomically: every pid folds into a fresh
        ref BEFORE any original drops, so a mid-mapping failure (or a
        dtype disable) leaves the original, correct refs in place."""
        out = {}
        blocks_in = sum(len(refs) for refs in mapping.values())
        try:
            with _trace.span("pipeline", self.label, lane="pipeline",
                             blocks=blocks_in):
                for pid, refs in mapping.items():
                    if not refs:
                        continue
                    if self._disabled:
                        raise _FoldDeclined()
                    blocks = [r.get() for r in refs]
                    merged = (blocks[0] if len(blocks) == 1
                              else Block.concat(blocks))
                    del blocks
                    if not self._value_dtype_ok(merged):
                        # Ineligible value lane: disable for the whole
                        # stage (one dtype per stage output).
                        with self._cv:
                            self._disabled = True
                            self._cv.notify_all()
                        raise _FoldDeclined()
                    folded = segment.fold_block(merged, self.op)
                    out[pid] = [self.store.register(folded,
                                                    device=self.device)]
        except _FoldDeclined:
            for refs in out.values():
                for r in refs:
                    self.store.drop_ref(r)
            return None
        except Exception:
            for refs in out.values():
                for r in refs:
                    self.store.drop_ref(r)
            raise
        for pid, refs in mapping.items():
            if pid in out:
                self.stats["early_folded_blocks"] += len(refs)
                for r in refs:
                    self.store.drop_ref(r)
            else:
                out[pid] = list(refs)
        return out

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            idx, mapping, nb = item
            t0 = time.perf_counter()
            try:
                if not self._disabled:
                    replacement = self._fold_one(idx, mapping)
                    if replacement is not None:
                        self.folded[idx] = replacement
                        self.stats["bytes_out"] += sum(
                            ref.total_bytes for refs in replacement.values()
                            for ref in refs)
                        self._note_delta(mapping, replacement)
            except Exception:  # noqa: BLE001 - folding is an optimization;
                #               originals stay registered, the run is fine
                _obslog.warn("early-fold-error",
                             "early-fold worker failed; disabling folding "
                             "for this stage (originals kept)",
                             logger=log, exc_info=True)
                with self._cv:
                    self._disabled = True
                    self._cv.notify_all()
            finally:
                dt = time.perf_counter() - t0
                self.stats["fold_seconds"] += dt
                if self._pool_done_at is None:
                    self.stats["overlap_seconds"] += dt
                self.store.release_overlap(nb)
                with self._cv:
                    self._pending = max(0, self._pending - nb)
                    self._sample_depth()
                    self._cv.notify_all()

    def _note_delta(self, mapping, replacement):
        """Per-pid staged-vs-folded byte delta: the reduce's size gates
        (tiny fast path) must decide on STAGED bytes, or the pipelined
        run could take a different branch than the staged one."""
        for pid, refs in mapping.items():
            orig = sum(r.total_bytes for r in refs)
            now = sum(r.total_bytes for r in replacement.get(pid, ()))
            self.fold_delta[pid] = self.fold_delta.get(pid, 0) + max(
                0, orig - now)

    def mark_pool_done(self):
        """Called when the map stage's job pool returns: fold seconds
        after this point no longer overlap map compute."""
        self._pool_done_at = time.perf_counter()

    def finish(self):
        """Drain, join, and return {idx: replacement mapping}."""
        self._q.put(None)
        self._thread.join(timeout=60.0)
        if self._thread.is_alive():
            # Wedged folder at shutdown: stop consuming its results (the
            # originals are still registered and correct) and let the
            # daemon thread release its reservations as it drains.
            _obslog.warn("early-fold-stuck",
                         "early-fold worker did not drain within 60s; "
                         "using unfolded mappings", logger=log)
            with self._cv:
                self._disabled = True
                self._cv.notify_all()
            return {}, dict(self.stats)
        return dict(self.folded), dict(self.stats)


class _ChainedOutput(object):
    """Placeholder env entry for a streamed chain producer's output
    (docs/pipeline.md): the stage's blocks flowed straight into the
    consumer's jobs and were dropped as each one was consumed — nothing
    ever materialized.  Duck-types the probes stage bookkeeping applies
    to arbitrary env entries (cleanup ignores non-PartitionSets)."""

    __slots__ = ("records",)

    def __init__(self, records):
        self.records = records

    def total_records(self):
        return self.records


class _SharedScanChunk(object):
    """One-read view of a tap chunk shared by scan-fused map stages: the
    first read_bytes() materializes, later readers (including streaming
    iter_byte_blocks consumers) serve from the cache.  If nothing ever
    materializes, iter_byte_blocks delegates to the chunk's own bounded
    scan — fusion never raises the memory ceiling above what the widest
    member would have used alone."""

    def __init__(self, chunk):
        self._chunk = chunk
        self._bytes = None

    def read_bytes(self):
        if self._bytes is None:
            self._bytes = self._chunk.read_bytes()
        return self._bytes

    def __getattr__(self, name):
        if name == "iter_byte_blocks":
            if self._bytes is not None:
                cached = self._bytes
                # accept (and ignore) block_size etc. like the real method
                return lambda *a, **k: iter((cached,))
            return getattr(self._chunk, name)  # AttributeError if absent
        return getattr(self._chunk, name)


class _RawRef(object):
    """Minimal in-memory stand-in for BlockRef when an OutputDataset has no
    store (direct construction in tests/tools)."""

    __slots__ = ("_block",)

    def __init__(self, block):
        self._block = block

    def get(self):
        return self._block

    def delete(self):
        self._block = None


class OutputDataset(Dataset):
    """Final-output view over a PartitionSet: reads records in ascending key
    order (the reference heap-merges sorted partition runs —
    runner.py:352-374).  Each partition is sorted independently and the
    partitions stream through a lazy k-way heap merge, so ``read(k)`` never
    materializes one giant concatenated copy and peak memory is the sum of
    partition working sets, not 2x the output."""

    def __init__(self, pset, store=None):
        self.pset = pset
        self.store = store
        self._range_cache = None  # mesh range-sort bucket runs, reused
        #                           across reads, released in delete()

    def _partition_stream(self, pid):
        from .dataset import OrderKey

        try:
            blk = self._sorted_partition_block(pid)
        except TypeError:
            # Uncomparable mixed keys: stable Python sort under the
            # total-order wrapper (rare path, matches the merge order).
            blk = Block.concat([r.get() for r in self.pset.refs(pid)])
            keys = blk.keys
            order = np.asarray(
                sorted(range(len(blk)), key=lambda i: OrderKey(keys[i])),
                dtype=np.int64)
            blk = blk.take(order)
        if blk is None:
            return iter(())
        return blk.iter_pairs()

    def _sorted_concat(self):
        """Vectorized fast path: one concat + stable argsort of the whole
        output.  Returns None when it shouldn't run — the working copies
        (refs + concat + take) peak near 3x the output size, so it is gated
        at a third of the memory budget; uncomparable mixed keys also bail
        to the streamed merge."""
        total = sum(r.total_bytes for r in self.pset.all_refs())
        budget = (self.store.budget if self.store is not None
                  else settings.max_memory_per_stage)
        if total * 3 > budget:
            return None
        blk = Block.concat([r.get() for r in self.pset.all_refs()])
        if not len(blk):
            return blk
        try:
            order = np.argsort(blk.keys, kind="stable")
        except TypeError:
            return None
        return blk.take(order)

    def _merged_run_blocks(self):
        """Stream a key-sorted run set (the spill-lean sort layout) through
        the vectorized k-way merge: one in-flight window per run, every run
        file read sequentially front to back, no read-side re-sort.  The
        write-side merge planner already capped the fan-in, so the working
        set is bounded."""
        from .blocks import merge_sorted_streams

        refs = [r for r in self.pset.all_refs() if len(r)]
        if not refs:
            return iter(())
        return merge_sorted_streams([r.iter_windows() for r in refs])

    def _key_sorted_blocks(self):
        """Sorted-block iterator for a key-sorted run set.  Multi-device
        meshes keep the collective range exchange — global order
        parallelizes across devices; single-device (the gate declines)
        streams the k-way merge."""
        blocks = self._mesh_range_sorted(sorted(self.pset.parts))
        if blocks is None:
            blocks = self._merged_run_blocks()
        return blocks

    def read(self):
        import itertools

        pids = sorted(self.pset.parts)
        if not pids:
            return iter(())
        if getattr(self.pset, "key_sorted_runs", False):
            # sorted_blocks() handles the whole strategy ladder (small
            # concat, mesh range exchange, streamed k-way merge).
            return itertools.chain.from_iterable(
                b.iter_pairs() for b in self.sorted_blocks())
        if len(pids) == 1:
            return self._partition_stream(pids[0])
        blk = self._sorted_concat()
        if blk is not None:
            return blk.iter_pairs()
        blocks = self._mesh_range_sorted(pids)
        if blocks is None:
            blocks = self._vector_merge_blocks(pids)
        if blocks is not None:
            return itertools.chain.from_iterable(
                b.iter_pairs() for b in blocks)
        return self._merge_partitions(pids)

    def _merge_partitions(self, pids):
        from .dataset import StreamDataset, merged_read

        streams = [StreamDataset(self._partition_stream(pid)) for pid in pids]
        return merged_read(streams)

    def _sorted_partition_block(self, pid):
        blk = Block.concat([r.get() for r in self.pset.refs(pid)])
        if not len(blk):
            return None
        order = np.argsort(blk.keys, kind="stable")  # TypeError -> caller
        return blk.take(order)

    def _mesh_range_sorted(self, pids, chunk=1 << 16):
        """sort_by's redistribution on the mesh: numeric-keyed partitions
        re-partition by key *range* across the devices — sampled quantile
        bounds route every record through the collective byte exchange to
        device ``bucket`` (bucket b ≡ pid b, so ``pid % D`` lands it
        there) — and global order becomes bucket order, each bucket merged
        independently.  Returns a sorted-block generator, or None when the
        mesh path is off, single-device, or keys are non-numeric."""
        if self._range_cache is None:
            budget = (self.store.budget if self.store is not None
                      else settings.max_memory_per_stage)
            gate = _exchange_mesh_gate(
                budget, getattr(self.pset, "shuffle_target", None))
            if gate is None:
                return None
            mesh, D, window = gate
            refs = [r for pid in pids for r in self.pset.refs(pid)]
            if not refs:
                return iter(())
            if any(getattr(r, "key_dtype", np.dtype(object)) == object
                   for r in refs):
                return None
            from .parallel import exchange as px

            # Range bounds from a strided sample.  Hash-partitioned runs
            # are key-random, so ONE window per ref samples uniformly.
            # Key-sorted runs are ordered WITHIN each run, so early
            # windows hold only that run's smallest keys — but each run
            # is one whole input chunk, so for unordered input every run
            # spans the full key distribution and a FEW runs read end to
            # end sample it faithfully; for pre-sorted input the runs
            # cover disjoint ranges, which striding the run choice across
            # the ref list covers.  Cost: <= 8 runs re-read (keys only),
            # against a pass that re-reads everything anyway.
            sorted_runs = bool(getattr(self.pset, "key_sorted_runs", False))
            if sorted_runs:
                # linspace, not a stride: the chosen runs must span BOTH
                # ends of the ref list, or pre-sorted input (runs with
                # disjoint ascending ranges) leaves the top of the key
                # space unsampled and overloads the last bucket.
                idx = np.unique(np.linspace(
                    0, len(refs) - 1, min(8, len(refs))).astype(int))
                sample_refs = [refs[i] for i in idx]
                per = max(16, 65536 // max(1, sum(
                    max(1, len(r) >> 14) for r in sample_refs)))
            else:
                sample_refs = refs
                per = max(16, 65536 // len(refs))
            samples = []
            for r in sample_refs:
                for wi, w in enumerate(r.iter_windows()):
                    if len(w):
                        stride = max(1, len(w) // per)
                        samples.append(np.asarray(w.keys[::stride]))
                    if not sorted_runs and wi == 0:
                        break
            if not samples:
                return iter(())
            allk = np.concatenate(samples)
            bounds = np.quantile(allk, np.linspace(0, 1, D + 1)[1:-1])

            bucket_refs = [[] for _ in range(D)]
            state = {"batch": [], "bytes": 0, "seq": 0}

            def flush():
                if not state["batch"]:
                    return
                received, _moved = px.mesh_shuffle_blocks(
                    mesh, state["batch"])
                for b, blk in received:
                    # store each bucket piece key-sorted: a mergeable run
                    order = np.argsort(blk.keys, kind="stable")
                    bucket_refs[b].append(
                        self.store.register(blk.take(order))
                        if self.store is not None
                        else _RawRef(blk.take(order)))
                state["batch"], state["bytes"] = [], 0

            for r in refs:
                for w in r.iter_windows():
                    if not len(w):
                        continue
                    keys = np.asarray(w.keys)
                    bidx = np.searchsorted(bounds, keys)
                    order = np.argsort(bidx, kind="stable")
                    sb = bidx[order]
                    edges = np.flatnonzero(np.diff(sb)) + 1
                    at = 0
                    for end in list(edges) + [len(sb)]:
                        if end > at:
                            b = int(sb[at])
                            state["batch"].append(
                                (state["seq"], state["seq"] % D, b,
                                 w.take(order[at:end])))
                            state["seq"] += 1
                            state["bytes"] += w.nbytes() * (end - at) // max(
                                1, len(w))
                        at = end
                    if state["bytes"] >= window:
                        flush()
            flush()
            # The bucket runs ARE the sorted materialization: cache them so
            # repeated reads reuse one exchange, and release them (only) in
            # delete() — abandoned read iterators cannot leak refs.  This
            # build may run AFTER the stage walk (lazy post-run reads), so
            # settle any spills its registrations queued: no other barrier
            # will run for them.
            if self.store is not None:
                self.store.drain_writes()
            self._range_cache = bucket_refs

        def gen():
            for brefs in self._range_cache:
                parts = [ref.get() for ref in brefs]
                for blk in self._merge_sorted_parts(parts, chunk):
                    yield blk

        return gen()

    def _vector_merge_blocks(self, pids, chunk=1 << 16):
        """K-way merge of key-sorted numeric-keyed partitions, emitted as
        blocks in bounded vectorized chunks: each round advances to the
        smallest partition-chunk boundary key, gathers every record at or
        below it via searchsorted, and stable-sorts only that slice —
        replacing per-record Python heap merging.  Returns None (fall back to
        the record merge) when any partition's keys are non-numeric."""
        all_refs = [r for pid in pids for r in self.pset.refs(pid)]
        if any(getattr(r, "key_dtype", np.dtype(object)) == object
               for r in all_refs):
            return None
        # Per-partition sorts run on the pool: numpy's sort kernels release
        # the GIL, so multi-core hosts get near-linear speedup on the read
        # phase's dominant cost (this bench box has one core; the path is
        # exercised by the multi-core CI rig either way).
        workers = max(1, min(settings.max_processes, len(pids)))
        if workers > 1:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                sorted_parts = list(pool.map(self._sorted_partition_block,
                                             pids))
        else:
            sorted_parts = [self._sorted_partition_block(p) for p in pids]
        parts = [blk for blk in sorted_parts if blk is not None]
        if not parts:
            return iter(())

        return self._merge_sorted_parts(parts, chunk)

    @staticmethod
    def _merge_sorted_parts(parts, chunk=1 << 16):
        """Vectorized k-way merge over key-sorted blocks (see
        _vector_merge_blocks for the chunking/tie rules)."""
        parts = [p for p in parts if len(p)]

        def slice_of(blk, a, b):
            return Block(
                blk.keys[a:b], blk.values[a:b],
                None if blk.h1 is None else blk.h1[a:b],
                None if blk.h2 is None else blk.h2[a:b])

        def gen():
            pos = [0] * len(parts)
            n_parts = len(parts)
            while True:
                bound = None
                active = False
                for i in range(n_parts):
                    blk = parts[i]
                    if pos[i] >= len(blk):
                        continue
                    active = True
                    edge = min(pos[i] + chunk, len(blk)) - 1
                    k = blk.keys[edge]
                    if bound is None or k < bound:
                        bound = k
                if not active:
                    return
                # Records strictly below the bound: at most `chunk` per
                # partition by construction, so this gather is bounded —
                # stable sort keeps partition-order ties like the heap merge.
                pieces = []
                for i in range(n_parts):
                    blk = parts[i]
                    if pos[i] >= len(blk):
                        continue
                    end = int(np.searchsorted(blk.keys, bound, side="left"))
                    if end > pos[i]:
                        pieces.append(slice_of(blk, pos[i], end))
                        pos[i] = end
                if pieces:
                    merged = Block.concat(pieces)
                    yield merged.take(
                        np.argsort(merged.keys, kind="stable"))
                # Records equal to the bound need no sorting: emit them as
                # raw partition-order slices in bounded pieces, so a hot key
                # with millions of duplicates streams instead of
                # materializing (the heap merge's tie order is partition
                # order, preserved here).
                for i in range(n_parts):
                    blk = parts[i]
                    if pos[i] >= len(blk):
                        continue
                    end = int(np.searchsorted(blk.keys, bound, side="right"))
                    at = pos[i]
                    while at < end:
                        sub = min(at + chunk, end)
                        yield slice_of(blk, at, sub)
                        at = sub
                    pos[i] = end

        return gen()

    def sorted_blocks(self):
        """Bulk access: the key-sorted output as columnar blocks.  Under a
        third of the memory budget: one concatenated sorted block.  Numeric
        keys over budget: the vectorized k-way merge (block sizes bounded by
        ~chunk x partitions, not settings.batch_size).  Otherwise: the
        per-record merge re-blocked at batch_size."""
        blk = self._sorted_concat()
        if blk is not None:
            if len(blk):
                yield blk
            return
        if getattr(self.pset, "key_sorted_runs", False):
            for b in self._key_sorted_blocks():
                yield b
            return
        pids = sorted(self.pset.parts)
        blocks = self._mesh_range_sorted(pids)
        if blocks is None:
            blocks = self._vector_merge_blocks(pids)
        if blocks is not None:
            for b in blocks:
                yield b
            return
        builder = BlockBuilder(settings.batch_size)
        for k, v in self._merge_partitions(pids):
            out = builder.add(k, v)
            if out is not None:
                yield out
        out = builder.flush()
        if out is not None:
            yield out

    def delete(self):
        if self._range_cache is not None:
            for brefs in self._range_cache:
                for ref in brefs:
                    if self.store is not None:
                        self.store.drop_ref(ref)
                    else:
                        ref.delete()
            self._range_cache = None
        self.pset.delete(self.store)


class StageStats(object):
    """Per-stage observability (the reference has log lines only — SURVEY §5
    commits to structured metrics).

    Beyond the original jobs/records/seconds triple this now carries the
    stage's IO shape (records/bytes in and out, best-effort: taps whose
    size is unknowable report 0 in) and the store-pressure deltas measured
    while the stage ran — spill volume, merge generations, retries.  Spill
    attribution is *causal*: a spill is charged to the stage whose
    registration pressure evicted the block, which may have been produced
    by an earlier stage."""

    __slots__ = ("stage_id", "kind", "n_jobs", "records_in", "records_out",
                 "bytes_in", "bytes_out", "spill_count", "spill_bytes",
                 "merge_gens", "merge_gen_bytes", "retries", "quarantined",
                 "seconds", "target", "shuffle_target")

    def __init__(self, stage_id, kind):
        self.stage_id = stage_id
        self.kind = kind
        # Execution target the plan's lowering pass assigned ("host" |
        # "device"); device map stages ran the jitted tokenize+hash+fold
        # programs, device reduces the segment kernels.
        self.target = "host"
        # Host-vs-mesh shuffle routing the plan's cost layer chose for
        # this stage's redistribution (None = not a redistribution stage,
        # or routing off).
        self.shuffle_target = None
        self.n_jobs = 0
        self.records_in = 0
        self.records_out = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.spill_count = 0
        self.spill_bytes = 0
        self.merge_gens = 0
        self.merge_gen_bytes = 0
        self.retries = 0
        # Poison records this stage skipped into the quarantine sink
        # (settings.max_quarantined; see dampr_tpu.faults.Quarantine).
        self.quarantined = 0
        self.seconds = 0.0

    def as_dict(self):
        return {"stage": self.stage_id, "kind": self.kind,
                "target": self.target,
                "jobs": self.n_jobs,
                "records_in": self.records_in,
                "records_out": self.records_out,
                "bytes_in": self.bytes_in, "bytes_out": self.bytes_out,
                "spill_count": self.spill_count,
                "spill_bytes": self.spill_bytes,
                "merge_gens": self.merge_gens,
                "merge_gen_bytes": self.merge_gen_bytes,
                "retries": self.retries,
                "quarantined": self.quarantined,
                "shuffle_target": self.shuffle_target,
                "seconds": round(self.seconds, 4)}


class MTRunner(object):
    """The scheduler: sequential stage walk, parallel jobs within a stage
    (reference MTRunner, runner.py:235-374)."""

    def __init__(self, name, graph, n_maps=None, n_reducers=None,
                 n_partitions=None, memory_budget=None, resume=False):
        self.name = name
        self.graph = graph
        self.resume = bool(resume)
        self.n_maps = n_maps or settings.max_processes
        self.n_reducers = n_reducers or settings.max_processes
        self.n_partitions = n_partitions or settings.partitions
        # Logical plan optimizer state (dampr_tpu.plan): the report lands
        # here when the plan is applied (by the DSL entry points or by
        # run() below — first caller wins) and feeds the run summary's
        # "plan" section.  An explicitly-passed partition count is pinned:
        # the cost layer's adaptive sizing only retunes the default.
        self.plan_report = None
        self._explicit_partitions = n_partitions is not None
        self.store = storage.RunStore(name, budget=memory_budget)
        self.stats = []
        self.mesh_folds = 0  # reduces executed via the mesh collective path
        self.mesh_exchanges = 0  # general shuffles routed over all_to_all
        self.mesh_exchange_bytes = 0  # payload bytes that crossed the mesh
        self.mesh_exchange_steps = 0  # chunked collective steps executed
        self.mesh_exchange_peak_inflight = 0  # modeled per-step high-water
        # Host-vs-mesh shuffle routing per stage id, ridden here by the
        # plan layer (plan.lower.apply_shuffle) — a dispatch hint, not
        # stage options, so fingerprints never depend on history.
        self._shuffle_targets = {}
        # Producer stage ids whose output edge the plan marked
        # handoff="device" (cross-stage device-resident handoff): their
        # jobs keep program outputs HBM-resident for the consuming fold.
        # A dispatch decision like _shuffle_targets — never stage
        # options, so resume/cache fingerprints stay history-independent.
        self._handoff_sids = set()
        # Streamed stage edges (plan/pipeline.py): producer sid -> edge
        # hint for the barrier-free executor.  Same dispatch-hint
        # discipline as _shuffle_targets/_handoff_sids — never stage
        # options, so fingerprints stay history-independent.
        self._pipeline_edges = {}
        # Per-run pipelined-execution accounting (stats()["pipeline"]).
        self._pipeline_stats = {
            "executed": 0, "degraded": 0, "published": 0,
            "early_folded_blocks": 0, "bytes_in": 0, "bytes_out": 0,
            "fold_seconds": 0.0, "overlap_seconds": 0.0,
            "stall_seconds": 0.0, "queue_peak_bytes": 0,
            "queue_depth_series": []}
        # Consumer-stage results a streamed chain computed ahead of the
        # stage walk (the consumer's loop turn consumes, not recomputes).
        self._chain_results = {}
        self.streamed_assoc_folds = 0  # over-budget vectorized accumulators
        self.retries_total = 0  # transient-failure job re-executions
        self._retry_lock = threading.Lock()
        self._backoff_seconds = 0.0  # classified-retry sleep total
        # Poison-record quarantine sink (settings.max_quarantined > 0):
        # deterministically-failing records on the batched-UDF path are
        # bisected out into <scratch>/<run>/quarantine.jsonl and the
        # stage completes; 0 keeps fail-fast.
        self._quarantine = (_faults.Quarantine(name,
                                               settings.max_quarantined)
                            if settings.max_quarantined > 0 else None)
        # Fault-injection counter epoch (process-cumulative counters;
        # finalize reports this run's deltas in stats()["faults"]).
        self._fault_snapshot = None
        # Run-scoped observability (dampr_tpu.obs): the tracer is live only
        # while settings.trace is on; run_summary (the stats.json dict) is
        # built for every run — it is how StageStats reaches users.
        self.tracer = None
        self.run_summary = None
        # Live metrics plane: registry + sampler while
        # settings.effective_metrics_interval_ms() > 0, flight recorder
        # whenever tracing or metrics is on, progress reporter under
        # settings.progress.  _status is the progress line's live stage
        # view (plain dict: single-writer per key, display-only reads).
        self.metrics = None
        self.flightrec = None
        self._sampler = None
        self._progress = None
        self._status = {}
        # Per-operator profiler (settings.profile): attributes fused-stage
        # time to individual user ops; summary ships as stats()["profile"].
        self.profiler = None
        # Live metrics endpoint (obs.serve, settings.metrics_port): one
        # stdlib HTTP thread per rank while the run is in flight.
        # _endpoint_info survives the server's teardown so finalize can
        # record the bound port (fallback included) in stats().
        self._metrics_server = None
        self._endpoint_info = None
        # Structured log stream (obs.log, settings.log_level): coded
        # JSONL events to <run>/trace/events.jsonl, WARN+ mirrored into
        # the flight recorder's crashdump tail.  None = every emit site
        # is one None-check.
        self.logstream = None
        # Per-run device-route accounting: snapshot of the exchange
        # module's cumulative per-device/per-route counters at run start,
        # differenced at finalize so stats() carries THIS run's matrix.
        self._exchange_snapshot = None
        # Straggler mitigation (parallel.mitigate, settings.mitigate):
        # work stealing + speculative re-execution on the host path,
        # live-skew degrade-in-place + sticky down-weighting on the
        # collective path.  Off = one None-check per site.
        self._mitigation = None
        # CAMR-style coded-exchange accounting (settings.exchange_coding):
        # window pre-folds traded for shuffle bytes, summed per run.
        self.coded_exchange = {"windows": 0, "raw_bytes": 0,
                               "coded_bytes": 0}
        # Failed runs must not feed the run-history corpus (their
        # measurements would poison the adaptation medians).
        self._run_failed = False
        # Cross-run materialization cache (plan/reuse.py,
        # settings.reuse): the live decision/byte counters that land as
        # stats()["reuse"].  None while the cache is off keeps untouched
        # runs free of the section (back-compat pin).
        self._reuse_summary = None

    # -- job fan-out --------------------------------------------------------
    def _speculation_ok(self, *stages):
        """May these stages' jobs be speculatively re-executed?  The
        static analyzer (settings.analyze) declines speculation for any
        stage holding an evidence-nondeterministic UDF — first-result-
        wins over a nondeterministic function commits whichever answer
        finished first, silently.  Only consulted when the mitigation
        controller is armed (the default path stays one None-check);
        ``assume_deterministic=True`` stage options suppress."""
        if not settings.analyze or _mitigate.active() is None:
            return True
        from .analyze import props

        for stage in stages:
            try:
                v = props.stage_verdict(stage)
            except Exception:  # noqa: BLE001 - analysis never fails a run
                continue
            if not v.deterministic:
                ctl = _mitigate.active()
                if ctl is not None:
                    ctl.note_speculation_declined(
                        v.name, v.nondet_evidence)
                return False
        return True

    def _pool_run(self, fn, jobs, n_workers, label=None, speculative=True,
                  on_result=None):
        """``on_result(idx, result)`` — the pipelined executor's publish
        hook — runs on the dispatching thread as each job's COMMITTED
        result is collected (attempt rollback, retries, and speculation
        all resolved), in job order.  It may block (backpressure); job
        workers keep running ahead, bounded by the store budget."""
        retries = settings.job_retries
        if retries:
            inner = fn

            def fn(job):  # noqa: F811 - deliberate retry wrapper
                for attempt in range(retries + 1):
                    try:
                        # attempt() rolls back this attempt's block
                        # registrations on failure so retries never orphan
                        # refs against the memory budget.
                        with self.store.attempt():
                            return inner(job)
                    except Exception as e:
                        # Classified retry (dampr_tpu.faults): fatal
                        # failures never re-execute; transient ones back
                        # off exponentially with jitter so a retry storm
                        # against a sick disk decorrelates; deterministic
                        # failures retry immediately (a stateful UDF may
                        # recover — the historical contract).
                        kind = _faults.classify(e)
                        if kind == "fatal" or attempt == retries:
                            raise
                        delay = (_faults.backoff(attempt)
                                 if kind == "transient" else 0.0)
                        ctl = _mitigate.active()
                        if ctl is not None and kind == "transient":
                            # Local transient-fault rate: shared with
                            # the fleet on the next exchange window's
                            # piggyback — a rank drowning in retries
                            # earns the sticky down-weight even before
                            # its step entries turn late.
                            ctl.note_local_retry()
                        with self._retry_lock:
                            self.retries_total += 1
                            self._backoff_seconds += delay
                        _trace.instant("retry", label or "job",
                                       attempt=attempt + 1, kind=kind)
                        log.warning(
                            "job failed (%s, attempt %d/%d), retrying"
                            "%s", kind, attempt + 1, retries + 1,
                            " in %.0f ms" % (delay * 1000) if delay
                            else "", exc_info=True)
                        if delay:
                            time.sleep(delay)

        if label is not None and _trace.enabled():
            traced = fn

            def fn(job, _inner=traced):  # noqa: F811 - span per job, on the
                #                          worker thread = one lane per slot
                with _trace.span("job", label):
                    return _inner(job)

        # Speculative duplicate attempts re-run the job but must not
        # re-run the one-call-per-job accounting below: the profiler's
        # job thread-seconds (the coverage denominator) and the
        # jobs_started/done counters both assume one counted call per
        # job — a losing duplicate would inflate them (10/8 jobs done).
        # Retry + trace-span wrappers DO apply to duplicates (a real
        # attempt deserves a real span).
        fn_speculative = fn

        prof = _profile.active()
        if prof is not None:
            # Per-stage job thread-seconds: the denominator of the
            # profiler's coverage metric (how much of the stage's job
            # time the per-op attribution explains).
            profiled = fn

            def fn(job, _inner=profiled):  # noqa: F811
                t0 = time.perf_counter()
                try:
                    return _inner(job)
                finally:
                    prof.job_add(time.perf_counter() - t0)

        m = _metrics.active()
        if m is not None:
            # Active-jobs accounting + the progress line's per-stage job
            # tally.  Outermost wrapper: a retried job counts once per
            # attempt started/done, so the active gauge stays balanced.
            st = self._status
            st["jobs_total"] = len(jobs)
            st["jobs_done"] = 0
            metered = fn

            def fn(job, _inner=metered):  # noqa: F811
                m.counter_add("run.jobs_started", 1)
                try:
                    return _inner(job)
                finally:
                    m.counter_add("run.jobs_done", 1)
                    st["jobs_done"] = st.get("jobs_done", 0) + 1

        def collect(results_iter):
            out = []
            for r in results_iter:
                if on_result is not None:
                    on_result(len(out), r)
                out.append(r)
            return out

        n_workers = max(1, min(n_workers, len(jobs), settings.max_processes))
        if n_workers == 1 or len(jobs) <= 1:
            return collect(fn(j) for j in jobs)
        ctl = _mitigate.active()
        if ctl is not None:
            # Mitigation-aware dispatch: rank-owned per-worker queues
            # with work stealing, plus speculative re-execution of
            # straggler jobs (first-result-wins under attempt-scoped
            # commits).  Sinks never speculate (duplicate part-file
            # writes would race on one path); quarantine-armed runs
            # don't either (a losing duplicate's quarantine commits
            # would double-count poison records against the budget).
            results = _mitigate.pool_dispatch(
                ctl, fn, jobs, n_workers, store=self.store,
                speculative=(speculative and self._quarantine is None),
                spec_fn=fn_speculative)
            if on_result is not None:
                # Mitigation dispatch returns only after every job
                # finished; publish post-hoc in order so the consumer
                # still sees each result exactly once (no overlap —
                # streamed stages degrade under an armed controller).
                for i, r in enumerate(results):
                    on_result(i, r)
            return results
        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            return collect(pool.map(fn, jobs))

    # -- pipelined (barrier-free) dispatch ---------------------------------
    def _stage_folder(self, stage_id, feeds_dev, run_mode, pin):
        """Early-fold folder for a streamed map->keyed-fold edge, or None
        when the edge isn't streamed (the common case: one dict probe) or
        a runtime condition degrades it back to the staged barrier."""
        hint = self._pipeline_edges.get(stage_id)
        if hint is None or hint["mode"] != "early_fold":
            return None
        if not settings.pipeline_enabled():
            return None
        degrade = None
        if _mitigate.active() is not None:
            degrade = "mitigation controller armed"
        elif run_mode or pin:
            degrade = "producer run mode incompatible"
        if degrade is None:
            cons = self.graph.stages[hint["dst"]]
            op = getattr(getattr(cons, "reducer", None), "op", None)
            if op is None or op.kind not in ("sum", "min", "max"):
                degrade = "consumer op not early-foldable"
        if degrade is not None:
            self._pipeline_stats["degraded"] += 1
            log.info("streamed edge s%s degraded to staged barrier: %s",
                     stage_id, degrade)
            return None
        bound = settings.pipeline_queue_bytes or max(
            1, self.store.budget // 4)
        self._pipeline_stats["executed"] += 1
        _trace.instant("pipeline", "streamed-edge", src=stage_id,
                       dst=hint["dst"], mode="early_fold")
        # feeds_dev rides through so folded replacements register in the
        # same tier the originals did (the reduce's device fold reads
        # them without an extra host round-trip).
        return _StreamFolder(self.store, op, bound, device=feeds_dev)

    def _note_pipeline(self, stage_id, fstats):
        """Merge one streamed edge's folder stats into the run total."""
        ps = self._pipeline_stats
        for k in ("published", "early_folded_blocks", "bytes_in",
                  "bytes_out", "fold_seconds", "overlap_seconds",
                  "stall_seconds"):
            ps[k] += fstats[k]
        ps["queue_peak_bytes"] = max(ps["queue_peak_bytes"],
                                     fstats["queue_peak_bytes"])
        series = ps["queue_depth_series"]
        series.extend([stage_id, t, b]
                      for t, b in fstats["queue_depth_series"])
        if len(series) > 1024:
            del series[: len(series) - 1024]

    def _wrap_chain_job(self, fn):
        """Retry + trace wrapper for chain consumer jobs: the _pool_run
        stack minus speculation (chain never speculates — duplicate
        consumer jobs would double-emit) and minus the per-stage job
        tally (the consumer's job count isn't known up front)."""
        retries = settings.job_retries

        def run(job):
            for attempt in range(retries + 1):
                try:
                    with self.store.attempt():
                        if _trace.enabled():
                            with _trace.span("job", "chain"):
                                return fn(job)
                        return fn(job)
                except Exception as e:
                    kind = _faults.classify(e)
                    if kind == "fatal" or attempt == retries:
                        raise
                    delay = (_faults.backoff(attempt)
                             if kind == "transient" else 0.0)
                    with self._retry_lock:
                        self.retries_total += 1
                        self._backoff_seconds += delay
                    _trace.instant("retry", "chain", attempt=attempt + 1,
                                   kind=kind)
                    log.warning(
                        "chain job failed (%s, attempt %d/%d), retrying%s",
                        kind, attempt + 1, retries + 1,
                        " in %.0f ms" % (delay * 1000) if delay else "",
                        exc_info=True)
                    if delay:
                        time.sleep(delay)
        return run

    def _run_chain(self, sid_p, stage_p, sid_c, env):
        """Streamed map->map chain (docs/pipeline.md): the consumer's
        jobs run per completed producer partition block while the
        producer stage is still executing, and the producer's output
        never materializes as a stage-boundary PartitionSet.

        Byte-identity contract: consumer results collect in the staged
        job order — (producer pid, producer job idx) — which is exactly
        the order the staged executor's ``all_refs()`` walk would have
        fed them in, and per-pid record order survives compaction's
        order-preserving concat on both legs.  Block BOUNDARIES differ
        (the staged leg compacts producer refs first), which the plan
        pass already proved invisible: chain edges require a pure record
        stream with no boundary-sensitive consumer downstream.

        Returns (producer placeholder, records, n_jobs) for the
        producer's stage bookkeeping and stashes the consumer's result
        in ``self._chain_results[sid_c]``; returns None to degrade to
        the staged barrier."""
        stage_c = self.graph.stages[sid_c]
        if _mitigate.active() is not None:
            self._pipeline_stats["degraded"] += 1
            log.info("streamed edge s%s degraded to staged barrier: "
                     "mitigation controller armed", sid_p)
            return None
        entries = [env[s] for s in stage_p.inputs]
        chunks = self._as_chunks(entries[0])
        supplementary = [self._as_chunks(e) for e in entries[1:]]
        (job_p, comb_p, pin_p, fr_p, _sp, dev_p, run_p,
         _wp) = self._map_job_factory(stage_p, supplementary, sid=sid_p)
        (job_c, comb_c, pin_c, fr_c, _sc, dev_c, run_c,
         _wc) = self._map_job_factory(stage_c, [], sid=sid_c)
        if (comb_p is not None or pin_p or fr_p or dev_p or run_p
                or comb_c is not None or pin_c or fr_c or dev_c or run_c):
            # The factories disagree with the plan-time gates (a settings
            # override between plan and run, or a shape the pass missed):
            # the staged barrier is always correct.
            self._pipeline_stats["degraded"] += 1
            log.info("streamed edge s%s degraded to staged barrier: "
                     "factory mode incompatible", sid_p)
            return None

        self._pipeline_stats["executed"] += 1
        _trace.instant("pipeline", "streamed-edge", src=sid_p, dst=sid_c,
                       mode="chain")
        n_maps = stage_p.options.get("n_maps", self.n_maps)
        wrapped_c = self._wrap_chain_job(job_c)
        futures = {}   # (producer pid, producer job idx) -> (future, refs)
        spans = []     # (t0, t1) per consumer job, for overlap accounting
        spans_lock = threading.Lock()
        acct = {"bytes_in": 0, "records_in": 0}

        def timed_c(ds, _run=wrapped_c):
            t0 = time.perf_counter()
            try:
                return _run(ds)
            finally:
                with spans_lock:
                    spans.append((t0, time.perf_counter()))

        def publish(idx, mapping):
            _faults.check("stream_publish")
            self._pipeline_stats["published"] += 1
            for pid in sorted(k for k in mapping if k != "_sorted"):
                refs = list(mapping[pid])
                if not refs:
                    continue
                acct["bytes_in"] += sum(r.total_bytes for r in refs)
                acct["records_in"] += sum(len(r) for r in refs)
                futures[(pid, idx)] = (
                    pool_c.submit(timed_c, BlockDataset(refs)), refs)

        pool_c = ThreadPoolExecutor(
            max_workers=max(1, min(n_maps, settings.max_processes)),
            thread_name_prefix="dampr-tpu-chain")
        pool_done_at = None
        try:
            self._pool_run(job_p, chunks, n_maps, label="map",
                           speculative=False, on_result=publish)
            pool_done_at = time.perf_counter()
            mappings_c = []
            for key in sorted(futures):
                fut, refs = futures[key]
                mappings_c.append(fut.result())
                for r in refs:
                    self.store.drop_ref(r)
        finally:
            pool_c.shutdown(wait=True)
        fold_s = sum(t1 - t0 for t0, t1 in spans)
        overlap_s = sum(max(0.0, min(t1, pool_done_at) - t0)
                        for t0, t1 in spans) if pool_done_at else 0.0
        bytes_out = sum(r.total_bytes for m in mappings_c
                        for refs in m.values() for r in refs)
        self._note_pipeline(sid_p, {
            "published": 0, "early_folded_blocks": 0,
            "bytes_in": acct["bytes_in"], "bytes_out": bytes_out,
            "fold_seconds": fold_s, "overlap_seconds": overlap_s,
            "stall_seconds": 0.0, "queue_peak_bytes": 0,
            "queue_depth_series": []})
        pset = self._collect_partitions(
            mappings_c, comb_c, pin_c, fr_c, device=dev_c,
            sorted_runs=run_c, handoff=sid_c in self._handoff_sids)
        self._chain_results[sid_c] = (
            pset, pset.total_records(), len(futures))
        return (_ChainedOutput(acct["records_in"]), acct["records_in"],
                len(chunks))

    # -- stage input views --------------------------------------------------
    def _as_chunks(self, entry):
        """Entry (tap Chunker or PartitionSet) -> list of map-job datasets
        (the DMChunker flattening, reference dataset.py:622-629)."""
        if isinstance(entry, storage.PartitionSet):
            ds = [BlockDataset([ref]) for ref in entry.all_refs()]
            return ds if ds else [BlockDataset([])]
        if isinstance(entry, _SinkOutput):
            return entry.datasets()
        assert isinstance(entry, Chunker), entry
        chunks = list(entry.chunks())
        return chunks if chunks else [BlockDataset([])]

    # -- map ---------------------------------------------------------------
    def run_map(self, stage_id, stage, env):
        entries = [env[s] for s in stage.inputs]
        chunks = self._as_chunks(entries[0])
        supplementary = [self._as_chunks(e) for e in entries[1:]]

        # Tiny-input collapse: a small materialized input to a plain record
        # mapper runs as ONE job over the concatenated refs instead of one
        # job per ref — per-job fixed costs dominate at this size.  Only
        # where chunking is mechanical, not semantic: pure record streams
        # (fused chains of plain Maps — is_pure_record_stream walks the
        # composition, since a fused chain can embed a StreamMapper whose
        # per-chunk invocation IS the semantics) and the broadcast joins
        # (which iterate the primary side record-wise).
        if (len(chunks) > 1
                and isinstance(entries[0], storage.PartitionSet)
                and (base.is_pure_record_stream(stage.mapper)
                     or type(stage.mapper) in (base.MapCrossJoin,
                                               base.MapAllJoin))):
            refs = list(entries[0].all_refs())
            if sum(getattr(r, 'total_bytes', r.nbytes)
                   for r in refs) <= settings.small_stage_bytes:
                chunks = [BlockDataset(refs)]

        (job, combine_op, pin, feeds_reduce, _new_sink,
         feeds_dev, run_mode, _wsink) = self._map_job_factory(
            stage, supplementary, sid=stage_id)

        n_maps = stage.options.get("n_maps", self.n_maps)
        folder = self._stage_folder(stage_id, feeds_dev=feeds_dev,
                                    run_mode=run_mode, pin=pin)
        replaced = {}
        try:
            results = self._pool_run(
                job, chunks, n_maps, label="map",
                speculative=self._speculation_ok(stage),
                on_result=folder.publish if folder is not None else None)
        finally:
            if folder is not None:
                # finish() drains even on a failed stage, so every queued
                # reservation releases — kill-mid-stream never orphans
                # queue entries against the budget.
                folder.mark_pool_done()
                replaced, fstats = folder.finish()
                self._note_pipeline(stage_id, fstats)
        for idx, mapping in replaced.items():
            if idx < len(results):
                results[idx] = mapping
        pset = self._collect_partitions(
            results, combine_op, pin, feeds_reduce, device=feeds_dev,
            sorted_runs=run_mode,
            handoff=stage_id in self._handoff_sids)
        if folder is not None and folder.fold_delta:
            # Staged-bytes pinning for the reduce's size gates: the tiny
            # fast path must branch on what the partition WOULD have
            # weighed unfolded, or pipeline on/off could take different
            # emit paths (hash-order vs key-order layouts).
            pset.pipeline_fold_delta = dict(folder.fold_delta)
        return pset, pset.total_records(), len(chunks)

    def _collect_partitions(self, mappings, combine_op, pin, feeds_reduce,
                            device=False, sorted_runs=False,
                            handoff=False):
        """Assemble per-chunk {pid: [refs]} job results into one compacted
        PartitionSet (shared by run_map and run_map_group).

        ``sorted_runs``: the jobs ran in spill-lean run mode — each mapping
        carries a ``_sorted`` marker recording whether every one of its
        blocks registered as a key-sorted run (numeric keys); the pset is
        flagged ``key_sorted_runs`` only when ALL jobs' blocks did, so the
        read-side streaming merge can trust every ref."""
        all_sorted = bool(sorted_runs)
        pset = storage.PartitionSet(
            self.n_partitions,
            hash_routed=not sorted_runs,
            hash_sorted=not sorted_runs and (combine_op is not None
                                             or feeds_reduce))
        for mapping in mappings:
            if sorted_runs and not mapping.pop("_sorted", False):
                all_sorted = False
            for pid, refs in mapping.items():
                for ref in refs:
                    pset.add(pid, ref)
        pset.key_sorted_runs = all_sorted
        if all_sorted and pset.parts:
            # Spill-lean path: no block-count compaction rewrite — merge
            # planning caps the read fan-in instead, and under the cap the
            # final read feeds straight from first-level runs.
            self._plan_sorted_merge(pset)
        else:
            self._compact_partitions(pset, combine_op, pin, feeds_reduce,
                                     device=device, handoff=handoff)
        return pset

    def _effective_merge_fanin(self, runs):
        """Fan-in cap for the sorted-run merge: the configured
        ``settings.merge_fanin``, clamped so the k-way merge's working set
        — one buffered spill window per run PLUS that run's bounded frame
        readahead (``settings.spill_read_prefetch`` windows in flight on
        the read executor), sized from the runs' observed bytes/record —
        stays inside the stage budget."""
        total = sum(max(1, r.total_bytes) for r in runs)
        nrec = sum(len(r) for r in runs)
        window = max(1, int(total / max(1, nrec)) * storage.SPILL_WINDOW)
        per_run = (2 + max(0, settings.spill_read_prefetch)) * window
        cap = max(4, int(self.store.budget // per_run))
        return max(2, min(settings.merge_fanin, cap))

    def _plan_sorted_merge(self, pset):
        """Merge planning for a key-sorted run set (the spill-lean external
        sort).  When the number of first-level runs fits the fan-in cap,
        nothing happens — the final read merges the runs directly, so the
        only bytes that ever hit disk are the map jobs' single spill
        generation.  Past the cap, a generation merges runs through
        streamed file->file passes (one in-flight window per source,
        output written as it merges — never RAM-resident whole) until the
        count fits, with two spill-lean refinements:

        - **minimal-touch planning**: only enough runs merge to bring the
          count under the cap — the smallest ones, so a run set just past
          the fan-in re-spills a fraction of its bytes, not all of them
          (merging m groups of <= g runs cuts the count by sum(g_i - 1),
          so m = ceil(excess / (g - 1)) merges suffice and everything
          else feeds the final read untouched);
        - **parallel generations**: the groups are independent, so they
          merge concurrently on a worker pool, each group's fan-in share
          capped at ``fanin // workers`` — the combined working set (one
          buffered window + prefetch per source across every concurrent
          merge) stays inside what the fan-in clamp budgeted.
        """
        from .blocks import merge_sorted_streams

        runs = [r for r in pset.all_refs() if len(r)]
        if not runs:
            return
        fanin = self._effective_merge_fanin(runs)
        gen = 0
        while len(runs) > fanin:
            # Worker count divides the fan-in budget: workers * group_cap
            # <= fanin, so the concurrent merges' combined working set
            # (one buffered window + prefetch per source) never exceeds
            # what _effective_merge_fanin budgeted — and group_cap >= 2
            # always (fanin >= 2), so every group genuinely reduces the
            # run count.
            workers = max(1, min(settings.max_processes, 8, fanin // 2))
            group_cap = max(2, fanin // workers)
            need = len(runs) - fanin
            m = max(1, -(-need // (group_cap - 1)))
            touched = need + m
            if touched > len(runs):
                # Far over the cap: every run merges this generation, in
                # groups of group_cap (the count still shrinks by a
                # group_cap factor per generation; the loop reruns).
                touched = len(runs)
                m = -(-touched // group_cap)
            # Smallest runs merge (fewest re-spilled bytes); the stride
            # split balances group sizes AND bytes across the workers.
            runs.sort(key=lambda r: r.total_bytes)
            to_merge = runs[:touched]
            keep = runs[touched:]
            groups = [g for g in (to_merge[i::m] for i in range(m)) if g]
            if _metrics.enabled():
                # Merge shape per generation: fan-in distribution and the
                # live run count the planner is working down.
                _metrics.counter_add("merge.generations", 1)
                _metrics.gauge_set("merge.runs", len(runs))
                for g in groups:
                    _metrics.observe("merge.fanin", len(g))
            log.info(
                "sorted-run merge generation: %d runs over fan-in %d — "
                "merging %d smallest into %d group(s) on %d worker(s)",
                len(runs), fanin, touched, len(groups),
                min(workers, len(groups)))

            def merge_group(group):
                if len(group) == 1:
                    return group[0]
                merged = self.store.register_stream(merge_sorted_streams(
                    [r.iter_windows() for r in group]))
                for r in group:
                    self.store.drop_ref(r)
                return merged

            # The generation gets its own trace lane, so Perfetto shows
            # merge generations stacked under the map slots they follow;
            # each group's streamed merge-run span lands on its worker
            # thread's lane.
            with _trace.span("merge", "generation {}".format(gen),
                             lane="merge gen {}".format(gen),
                             runs=len(runs), fanin=fanin,
                             groups=len(groups)):
                if len(groups) > 1 and workers > 1:
                    with ThreadPoolExecutor(
                            max_workers=min(workers, len(groups))) as pool:
                        merged = list(pool.map(merge_group, groups))
                else:
                    merged = [merge_group(g) for g in groups]
            runs = keep + merged
            gen += 1
        pset.parts = {0: runs}

    def _scan_share_group(self, sid, stage, env):
        """Later GMap stages reading the SAME tap source as `stage`: fusion
        candidates for one shared pass.  Only single-input stages over a
        Chunker tap (where IO is the dominant cost) qualify."""
        if not settings.scan_sharing or len(stage.inputs) != 1:
            return []
        if not isinstance(env.get(stage.inputs[0]), Chunker):
            return []
        group = []
        for sjd in range(sid + 1, len(self.graph.stages)):
            s2 = self.graph.stages[sjd]
            if (isinstance(s2, GMap) and len(s2.inputs) == 1
                    and s2.inputs[0] == stage.inputs[0]):
                group.append((sjd, s2))
        return group

    def run_map_group(self, sids, stages, env):
        """Scan sharing: execute several map stages over one pass of their
        common tap.

        Preferred path — every member exposes ``window_sink`` (the
        ops.text scanners): ONE line-aligned window pass per chunk fans
        each window out to every member's sink and pushes the resulting
        blocks straight into that member's fold/register pipeline, so the
        tap is read (and a .gz decompressed) exactly once with memory
        bounded by the window, never the chunk.

        Fallback — members that materialize bytes share one chunk read via
        the _SharedScanChunk cache (byte-materializing members run before
        streaming ones, Mapper.streams_bytes); per-record members read
        independently.  Returns one (pset, nrec, njobs) per stage, in the
        given order."""
        tap = env[stages[0].inputs[0]]
        chunks = self._as_chunks(tap)
        factories = [self._map_job_factory(s, [], sid=sjd)
                     for sjd, s in zip(sids, stages)]
        order = sorted(range(len(stages)),
                       key=lambda i: bool(
                           getattr(stages[i].mapper, "streams_bytes", False)))
        all_window = all(
            hasattr(s.mapper, "window_sink") for s in stages)

        def group_job(chunk):
            if all_window and hasattr(chunk, "iter_byte_blocks"):
                from .ops.text import _scan_windows

                members = []
                for i, s in enumerate(stages):
                    push, end = factories[i][4]()
                    # factories[i][7] is the target-aware window-sink
                    # factory: device-lowered members scan through the
                    # jitted programs, host members keep their own sink.
                    members.append((factories[i][7](), push, end))

                def codec():
                    # ONE sequential window pass drives every member's
                    # sink (sinks are stateful, so a single producer
                    # thread owns them); the emitted (member, block)
                    # pairs overlap with the fold/register consumer.
                    for win in _scan_windows(chunk):
                        for mi, (wsink, _push, _end) in enumerate(members):
                            for blk in wsink.add(win) or ():
                                yield mi, blk
                    for mi, (wsink, _push, _end) in enumerate(members):
                        for blk in wsink.finish() or ():
                            yield mi, blk

                gen = codec()
                prof = _profile.active()
                if prof is not None:
                    # The shared window pass serves EVERY member; its scan
                    # time is attributed once, under a label naming the
                    # fused scanners (per-member split is not observable —
                    # one producer thread drives all the sinks).
                    gen = prof.timed_iter(
                        gen, "scan:" + "+".join(
                            type(s.mapper).__name__ for s in stages),
                        records_of=lambda it: len(it[1]))
                for mi, blk in _overlap_stream(
                        gen, self.store,
                        size_of=lambda it: it[1].nbytes()):
                    members[mi][1](blk)
                outs_w = []
                for wsink, push_m, end_m in members:
                    hmap = None
                    if hasattr(wsink, "finalize_handoff"):
                        fblocks, hmap = wsink.finalize_handoff(
                            self.store, self.n_partitions)
                        for blk in fblocks:
                            push_m(blk)
                    o = end_m()
                    if hmap:
                        for pid, refs in hmap.items():
                            o.setdefault(pid, []).extend(refs)
                    outs_w.append(o)
                return outs_w
            shared = (_SharedScanChunk(chunk)
                      if hasattr(chunk, "read_bytes") else chunk)
            outs = [None] * len(stages)
            for i in order:
                outs[i] = factories[i][0](shared)
            return outs

        # Honor every member's explicit n_maps: the most restrictive wins,
        # so a stage that asked to serialize stays serialized when fused.
        n_maps = min(s.options.get("n_maps", self.n_maps) for s in stages)
        results = self._pool_run(group_job, chunks, n_maps,
                                 label="map-group",
                                 speculative=self._speculation_ok(*stages))

        ret = []
        for i in range(len(stages)):
            (_job, combine_op, pin, feeds_reduce, _new_sink,
             feeds_dev, run_mode, _wsink) = factories[i]
            pset = self._collect_partitions(
                [outs[i] for outs in results], combine_op, pin, feeds_reduce,
                device=feeds_dev, sorted_runs=run_mode,
                handoff=sids[i] in self._handoff_sids)
            ret.append((pset, pset.total_records(), len(chunks)))
        log.info("scan sharing: %d stages fused over one pass of %d chunks",
                 len(stages), len(chunks))
        return ret

    def _map_job_factory(self, stage, supplementary, sid=None):
        """Build the per-chunk job closure for one map stage.  Shared by
        run_map and the scan-sharing group executor (run_map_group), which
        runs several stages' jobs over one chunk read.  ``sid`` keys the
        plan's per-edge dispatch decisions (the device-handoff set)."""
        combine_op = None
        if isinstance(stage.combiner, base.PartialReduceCombiner):
            combine_op = stage.combiner.op
        elif "binop" in stage.options:
            combine_op = segment.as_assoc_op(stage.options["binop"])

        pin = bool(stage.options.get("memory"))
        P = self.n_partitions
        # Hash-sorted runs are only needed when a reduce consumes this output
        # (it's what the over-budget streaming merge relies on); stages
        # feeding sinks or final reads skip the sort — their consumers
        # re-order by key anyway.  The view is TRANSITIVE through identity
        # checkpoints: a reduce behind ``checkpoint(force=True)`` still
        # needs hash routing here, or the checkpoint's declined alias
        # forces a full re-routing copy pass over the dataset.
        feeds_reduce = self._reduce_consumes(stage.output)
        # HBM residency: outputs consumed by a device-foldable reduce keep
        # their numeric value lanes on device (storage register gates on
        # the lane whitelist + budget), so the map->reduce boundary never
        # round-trips those lanes through host memory.
        feeds_device_fold = (
            feeds_reduce
            and settings.use_device
            and str(settings.mesh_fold).lower() not in ("off", "0", "false")
            and any(
                isinstance(s, GReduce) and stage.output in s.inputs
                and len(s.inputs) == 1
                and isinstance(getattr(s, "reducer", None),
                               base.AssocFoldReducer)
                and getattr(getattr(s.reducer, "op", None), "kind", None)
                in ("sum", "min", "max")
                for s in self.graph.stages))
        # Spill-lean sorted-run mode (external sorts): outputs no reduce
        # consumes don't need hash fan-out at all — their only readers
        # re-order by key (OutputDataset) or stream refs whole (sinks,
        # record maps).  Each job registers its chunk as ONE key-sorted run
        # instead of `partitions` hash-routed sub-blocks; the compaction
        # rewrite is replaced by fan-in-capped merge planning and the final
        # read streams a k-way merge.  Jobs fall back to hash fan-out per
        # chunk when keys aren't uniformly numeric (the ``_sorted`` marker
        # records which happened).
        sorted_run_mode = (settings.sort_runs_enabled()
                           and combine_op is None
                           and not feeds_reduce
                           and not pin
                           and not supplementary)

        def try_sorted_run(blocks):
            """Register one key-sorted run for this job, or None when the
            keys don't qualify (caller falls back to hash fan-out)."""
            blocks = [b for b in blocks if len(b)]
            if not blocks:
                return {"_sorted": True}
            kdts = {b.keys.dtype for b in blocks}
            if len(kdts) != 1 or next(iter(kdts)).kind not in "iuf":
                return None
            if (next(iter(kdts)).kind == "f"
                    and any(np.isnan(b.keys).any() for b in blocks)):
                # NaN has no total order: a NaN-tailed run would break
                # the k-way merge's non-decreasing emission contract
                # (NaN poisons the bound comparisons).  Hash fan-out
                # handles NaN keys the same way it always has.
                return None
            merged = blocks[0] if len(blocks) == 1 else Block.concat(blocks)
            merged = merged.take(np.argsort(merged.keys, kind="stable"))
            return {0: [self.store.register(merged)], "_sorted": True}

        def new_sink():
            """Push-mode accumulator for one chunk job: push(blk) folds/
            collects, end() registers and returns the partition mapping.
            The scan-sharing group executor pushes blocks from a SHARED
            window pass into several stages' sinks."""
            raw, partials = [], []

            def push(blk):
                if blk is None or not len(blk):
                    return
                if combine_op is not None:
                    _faults.check("fold")
                    prof = _profile.active()
                    t0p = time.perf_counter() if prof is not None else 0.0
                    with _trace.span("fold", "partial-fold",
                                     records=len(blk)):
                        partials.append(segment.fold_block(blk, combine_op))
                        if len(partials) >= _PARTIAL_FANIN:
                            merged = segment.fold_block(
                                Block.concat(partials), combine_op)
                            del partials[:]
                            partials.append(merged)
                    if prof is not None:
                        prof.op_add("combine", time.perf_counter() - t0p,
                                    records=len(blk))
                else:
                    raw.append(blk)

            def end():
                blocks = raw
                if combine_op is not None and partials:
                    prof = _profile.active()
                    t0p = time.perf_counter() if prof is not None else 0.0
                    with _trace.span("fold", "final-fold"):
                        blocks = [segment.fold_block(
                            Block.concat(partials), combine_op)]
                    if prof is not None:
                        prof.op_add("combine", time.perf_counter() - t0p)
                if sorted_run_mode:
                    out = try_sorted_run(blocks)
                    if out is not None:
                        return out
                # Register with the store *inside* the job so the memory
                # budget is enforced while the stage runs, not after all
                # jobs complete.  Every registered block is a hash-sorted
                # run (fold outputs already are; raw blocks sort here —
                # stable, so equal keys keep input order), which is what
                # lets over-budget reduces stream a k-way merge instead of
                # materializing the partition.
                out = {}
                for blk in blocks:
                    if combine_op is None and feeds_reduce:
                        blk = blk.sort_by_hash()
                    for pid, sub in blk.split_by_partition(P).items():
                        out.setdefault(pid, []).append(
                            self.store.register(sub, pin=pin,
                                                device=feeds_device_fold,
                                                handoff=stage_handoff))
                return out

            return push, end

        # Per-stage block sizing: the plan's cost layer may have set a
        # batch_size option from observed bytes/record history.
        stage_batch = stage.options.get("batch_size") or settings.batch_size

        # Device-lowered stage (plan.lower assigned exec_target): the
        # scanner's window pass runs through the jitted tokenize+hash+fold
        # programs instead of the host codec.  claims() re-checks the
        # mapper so a stale/foreign annotation can never dispatch an
        # unrecognized op — the host path below is the guaranteed fallback.
        # Cross-stage device handoff (plan.lower handoff_analyze): this
        # stage's output edge keeps program outputs HBM-resident for the
        # consuming device fold.  A runtime dispatch hint keyed by sid —
        # deliberately NOT stage options (fingerprints stay
        # history-independent).
        stage_handoff = sid is not None and sid in self._handoff_sids
        dev_lowered = False
        lane_program = None
        if stage.options.get("exec_target") == "device":
            from .ops import lower as ops_lower

            dev_lowered = ops_lower.claims(stage.mapper) is not None
            if not dev_lowered:
                # Certified numeric UDF chain (analyze.jaxtrace, the
                # widened ROADMAP-5a vocabulary): the batched-UDF path
                # below runs whole batches through one vectorized lane
                # program.  stage_program re-certifies the chain, so a
                # stale/foreign annotation can never dispatch an
                # unknown op; non-numeric batches fall back per batch.
                from .analyze import jaxtrace as _jaxtrace

                lane_program = _jaxtrace.stage_program(stage)

        def window_sink():
            """The stage's window sink honoring its execution target
            (shared with run_map_group's fused window pass)."""
            if dev_lowered:
                from .ops import lower as ops_lower

                return ops_lower.device_window_sink(
                    _clone_op(stage.mapper), self.store,
                    handoff=stage_handoff,
                    jobs=stage.options.get("n_maps", self.n_maps))
            return _clone_op(stage.mapper).window_sink()

        def job(chunk):
            mapper = _clone_op(stage.mapper)
            builder = BlockBuilder(stage_batch)
            # Attempt-scoped quarantine recorder: records isolated by
            # this attempt's bisect land in the global sink only when
            # the attempt SUCCEEDS (commit below), so a retried job
            # never double-counts and genuine duplicates each count.
            quarantine = self._quarantine
            qrec = quarantine.attempt() if quarantine is not None else None
            # Vectorized block protocol: mappers exposing map_blocks consume
            # the chunk's raw bytes and emit whole Blocks, skipping the
            # per-record Python path entirely (the SURVEY §7 dual-path).
            use_blocks = (not supplementary
                          and hasattr(mapper, "map_blocks")
                          and hasattr(chunk, "read_bytes"))
            # Identity stages (bare checkpoint/sink heads) pass blocks
            # through whole — the records are already materialized; walking
            # them one by one through Python buys nothing.
            ident_blocks = (not supplementary and not use_blocks
                            and type(mapper) is base.Map
                            and mapper.mapper is base._identity
                            and hasattr(chunk, "iter_blocks"))
            # Batched-UDF path (SURVEY §7 hard part 1): a chain of pure
            # RecordOps runs batch-at-a-time — read B records, run each
            # op's apply_batch over the whole batch, build the block from
            # the surviving lists.  Replaces the reference's per-record
            # generator hot loop (ref stagerunner.py:73-74).
            chain = (base.record_op_chain(mapper)
                     if settings.batch_udf and not supplementary
                     and not use_blocks and not ident_blocks else None)
            # Per-operator profiler (obs.profile): one hoisted None-check
            # per job; labels are index-prefixed so duplicate op types in
            # one fused chain stay distinct.
            prof = _profile.active()
            prof_labels = (_profile.chain_labels(chain)
                           if prof is not None and chain is not None
                           else None)
            push, end = new_sink()
            dev_sink = None
            if (dev_lowered and not supplementary
                    and (hasattr(chunk, "read_bytes")
                         or hasattr(chunk, "iter_byte_blocks"))):
                # Device-lowered scan: windows feed double-buffered jitted
                # programs (ops.lower); the producer thread tokenizes and
                # dispatches while this thread folds/registers the
                # vocabulary-sized partials.  Under a handoff="device"
                # edge the sink accumulates device-resident instead of
                # emitting — finalize below registers the HBM refs.
                from .ops import lower as ops_lower
                from .ops.text import _drive_windows

                dev_sink = ops_lower.device_window_sink(
                    mapper, self.store, handoff=stage_handoff,
                    jobs=stage.options.get("n_maps", self.n_maps))
                for blk in _overlap_stream(
                        _drive_windows(mapper, chunk, sink=dev_sink),
                        self.store):
                    push(blk)
            elif use_blocks:
                # Stage-overlapped streaming executor: the codec (window
                # scan + tokenize/parse inside map_blocks) runs ahead on
                # its own thread while this thread folds/registers, with
                # in-flight blocks charged against the run budget.
                blocks_iter = mapper.map_blocks(chunk)
                if prof is not None:
                    # Each produced window's decompress+tokenize time is
                    # the scanner op's attribution.
                    blocks_iter = prof.timed_iter(
                        blocks_iter, _profile.op_label(mapper, 0))
                for blk in _overlap_stream(blocks_iter, self.store):
                    push(blk)
            elif ident_blocks:
                for blk in chunk.iter_blocks():
                    push(blk)
            elif chain is not None:
                B = stage_batch
                reader = getattr(chunk, "read_lists", None)
                if reader is not None:
                    batches = reader(B)
                else:
                    def _islice_batches(it=iter(chunk.read())):
                        while True:
                            ks, vs = [], []
                            for k, v in itertools.islice(it, B):
                                ks.append(k)
                                vs.append(v)
                            if not ks:
                                return
                            yield ks, vs
                    batches = _islice_batches()
                # Surviving records accumulate across input batches so a
                # selective filter still emits ~B-record blocks (matching
                # BlockBuilder's coalescing on the generator path), while
                # FlatMap feeds in adaptive slices so B x fanout never
                # materializes at once — memory stays bounded either way.
                pk, pv = [], []

                def emit(ks, vs):
                    pk.extend(ks)
                    pv.extend(vs)
                    while len(pk) >= B:
                        push(Block.from_lists(pk[:B], pv[:B]))
                        del pk[:B]
                        del pv[:B]

                def run_chain(ks, vs, start, emit_fn):
                    for i in range(start, len(chain)):
                        op = chain[i]
                        if type(op) is base.FlatMap and len(ks) > 1024:
                            # Slice the expanding op's input, adapting to
                            # its observed fanout so each slice's output
                            # stays ~B; the rest of the chain runs per
                            # slice.  Slices preserve stream order, so
                            # batch/stream equivalence is unaffected.
                            n = len(ks)
                            at, step = 0, 1024
                            while at < n:
                                took = min(step, n - at)
                                t0p = (time.perf_counter()
                                       if prof is not None else 0.0)
                                sks, svs = op.apply_batch(
                                    ks[at:at + took], vs[at:at + took])
                                if prof is not None:
                                    prof.op_add(prof_labels[i],
                                                time.perf_counter() - t0p,
                                                records=len(sks))
                                at += took
                                if sks:
                                    fan = -(-len(sks) // took)
                                    step = max(64, min(B, B // fan))
                                    run_chain(sks, svs, i + 1, emit_fn)
                            return
                        if prof is None:
                            ks, vs = op.apply_batch(ks, vs)
                        else:
                            # One clock pair per op per BATCH — the
                            # sampled-timer discipline that keeps the
                            # profiled path inside the <=3% overhead gate.
                            t0p = time.perf_counter()
                            ks, vs = op.apply_batch(ks, vs)
                            prof.op_add(prof_labels[i],
                                        time.perf_counter() - t0p,
                                        records=len(ks))
                        if not ks:
                            return
                    emit_fn(ks, vs)

                fa = _faults.active()
                if quarantine is None and fa is None:
                    prog = lane_program
                    if prog is None:
                        # The hot default: straight through, zero added
                        # cost.
                        for ks, vs in batches:
                            run_chain(ks, vs, 0, emit)
                    else:
                        # Certified lane program: whole batches evaluate
                        # vectorized (64-bit host authority; device
                        # dispatch verified per batch inside run_batch).
                        # The FIRST vectorized batch of each job is
                        # additionally differential-tested against the
                        # per-record chain — a divergence (int64 wrap,
                        # a dtype-sensitive UDF) drops the job back to
                        # the authoritative per-record path for good.
                        diffed = False
                        for ks, vs in batches:
                            out = (prog.run_batch(ks, vs)
                                   if prog is not None else None)
                            if out is not None and not diffed:
                                diffed = True
                                staged = []
                                run_chain(ks, vs, 0,
                                          lambda a, b:
                                          staged.append((a, b)))
                                rks = [k for a, _ in staged for k in a]
                                rvs = [v for _, b in staged for v in b]
                                prog.count("diff_checked")
                                if rks != out[0] or rvs != out[1]:
                                    prog.count("diff_diverged")
                                    log.warning(
                                        "lane program diverged from the "
                                        "per-record chain on its first "
                                        "batch (%s); job falls back to "
                                        "the per-record path",
                                        prog.spec.describe())
                                    prog = None
                                    emit(rks, rvs)
                                    continue
                            if out is None:
                                run_chain(ks, vs, 0, emit)
                            else:
                                emit(*out)
                else:
                    # Poison-record quarantine (and/or fault injection):
                    # each input batch runs TRANSACTIONALLY — outputs
                    # stage into a local buffer and only merge into the
                    # block builder on success, so a deterministic
                    # failure mid-chain (or mid-FlatMap-slice) can be
                    # bisected and re-run without duplicating records.
                    # Order is preserved (left half before right half),
                    # so results are byte-identical to a run whose input
                    # simply lacked the quarantined records.
                    def guarded_run(ks, vs):
                        staged = []

                        def stage_emit(sks, svs):
                            staged.append((sks, svs))

                        try:
                            if fa is not None:
                                _faults.check_records("udf", ks, vs)
                            run_chain(ks, vs, 0,
                                      stage_emit if quarantine is not None
                                      else emit)
                        except Exception as e:
                            if (quarantine is None
                                    or _faults.classify(e)
                                    != "deterministic"):
                                raise
                            if len(ks) <= 1:
                                qrec.add(
                                    _faults.run_context.get("stage"),
                                    ks[0] if ks else None,
                                    vs[0] if vs else None, e)
                                return
                            with _trace.span("fault", "quarantine-bisect",
                                             records=len(ks)):
                                mid = len(ks) // 2
                                guarded_run(ks[:mid], vs[:mid])
                                guarded_run(ks[mid:], vs[mid:])
                            return
                        for sks, svs in staged:
                            emit(sks, svs)

                    for ks, vs in batches:
                        guarded_run(ks, vs)
                if pk:
                    push(Block.from_lists(pk, pv))
            else:
                kvs = (mapper.map(chunk, *supplementary) if supplementary
                       else mapper.map(chunk))
                if prof is not None and combine_op is None:
                    # Generator-path chains don't decompose per op (the
                    # fused generators interleave); attribute the whole
                    # stream to one chain-shaped label so coverage holds.
                    t0p = time.perf_counter()
                    nrec = 0
                    for k, v in kvs:
                        nrec += 1
                        push(builder.add(k, v))
                    push(builder.flush())
                    prof.op_add("stream:" + _profile.op_label(mapper),
                                time.perf_counter() - t0p, records=nrec)
                else:
                    for k, v in kvs:
                        push(builder.add(k, v))
                    push(builder.flush())
            hmap = None
            if dev_sink is not None:
                # Device-resident finalize: the accumulated vocabulary
                # becomes per-partition HBM refs; a budget-degrade flush
                # block rides the classic combine instead.
                fblocks, hmap = dev_sink.finalize_handoff(self.store, P)
                for blk in fblocks:
                    push(blk)
            out = end()
            if hmap:
                for pid, refs in hmap.items():
                    out.setdefault(pid, []).extend(refs)
            if qrec is not None:
                qrec.commit()
            return out

        return (job, combine_op, pin, feeds_reduce, new_sink,
                feeds_device_fold, sorted_run_mode, window_sink)

    def _compact_partitions(self, pset, combine_op, pin, feeds_reduce=True,
                            device=False, handoff=False):
        """Block-count governor (the reference's file-count combiner rounds,
        runner.py:293-320): partitions holding more than max_files_per_stage
        refs merge — re-folding under the stage's associative op when present
        — so ref counts and reduce-side fan-in stay bounded.

        Memory discipline: refs merge in rounds of at most ``limit`` at a
        time, and each round's source refs are dropped from the store before
        the merged block registers, so peak residency stays one round's worth
        over budget instead of the whole partition (and near-budget source
        refs never get pointlessly spilled just to be deleted)."""
        limit = max(2, settings.max_files_per_stage)
        for pid, refs in list(pset.parts.items()):
            if len(refs) > limit:
                _trace.instant("merge", "compact", partition=pid,
                               blocks=len(refs))
            while len(refs) > limit:
                merged_refs = []
                for at in range(0, len(refs), limit):
                    round_refs = refs[at:at + limit]
                    if len(round_refs) == 1:
                        merged_refs.append(round_refs[0])
                        continue
                    blocks = [r.get() for r in round_refs]
                    for r in round_refs:
                        self.store.drop_ref(r)
                    merged = Block.concat(blocks)
                    del blocks
                    if combine_op is not None:
                        merged = segment.fold_block(merged, combine_op)
                    elif feeds_reduce:
                        # keep the run invariant: merged blocks stay
                        # hash-sorted so streaming reduces can merge them
                        merged = merged.sort_by_hash()
                    # On a handoff edge the merged block re-enters the
                    # HBM tier at the edge's floor (the consuming fold
                    # reads it in place); the fetch above is the
                    # governor's one bounded host round trip per
                    # `limit` refs, honestly counted as d2h.
                    merged_refs.append(self.store.register(
                        merged, pin=pin, device=device or handoff,
                        handoff=handoff))
                refs = merged_refs
            pset.parts[pid] = refs

    # -- reduce ------------------------------------------------------------
    def _mesh_reduce(self, stage, entries):
        """Distributed fast path for device-foldable associative reduces:
        window-streamed mesh collective folds (local fold -> all_to_all by
        hash -> final fold per window, partials re-folded through the same
        program), so host memory is one window plus the distinct-key
        accumulator — never the partition set, which may be arbitrarily
        over-budget and spilled.  Returns None whenever the host path is
        required for exactness: object values, lane overflow (every
        mesh_keyed_fold call re-checks its inputs, and partial magnitudes
        are bounded by element magnitudes, so per-call checks compose),
        a 64-bit key collision, or accumulator cardinality past the budget."""
        mode = str(settings.mesh_fold).lower()
        if mode in ("off", "0", "false") or not settings.use_device:
            return None
        ctl = _mitigate.active()
        if ctl is not None and not ctl.collective_fold_ok():
            # Degrade-in-place: while the mitigation is engaged the
            # collective fold would re-serialize the fleet on the
            # straggler at every window — the host path is exact and
            # free-running.  Deterministic from shared controller state,
            # so every rank declines together (no one-sided collective).
            return None
        if len(entries) != 1 or not isinstance(stage.reducer,
                                               base.AssocFoldReducer):
            return None
        op = stage.reducer.op
        if op.kind not in ("sum", "min", "max"):
            return None
        refs = list(entries[0].all_refs())
        if (mode not in ("on", "1", "true")
                and settings.device_count_for_auto() < 2
                and not any(getattr(r, "is_device", False) for r in refs)):
            # Single device and nothing HBM-resident: the local fold path
            # is cheaper.  With device-resident inputs the mesh fold (D=1
            # degenerates to the plain collective program) IS the consumer
            # that keeps the value lanes from round-tripping through host.
            return None
        import jax

        if not refs:
            return storage.PartitionSet(self.n_partitions, hash_routed=True,
                                        hash_sorted=True), 0, 1
        # Cheap metadata check before touching any (possibly spilled) data.
        if any(getattr(r, "value_dtype", object) == object for r in refs):
            return None

        from .blocks import _concat_cols
        from .ops.hashing import combine64
        from .parallel import mesh_keyed_fold
        from .parallel.shuffle import mesh_keyed_refold
        from .parallel.mesh import data_mesh

        mesh = data_mesh()
        x64 = jax.config.jax_enable_x64
        window_budget = max(1 << 20, self.store.budget // 4)
        acc_budget = max(1 << 20, self.store.budget // 4)

        class _HostPath(Exception):
            pass

        # Distinct-key table: u64-sorted hash lanes with the matching keys,
        # kept as GEOMETRIC SEGMENTS (the logarithmic method): each window's
        # new keys append as one sorted segment; equal-size neighbors merge
        # pairwise, so every key participates in O(log W) linear merges —
        # replacing a per-window np.insert whose O(table) rebuild degraded
        # quadratically on high-cardinality folds.  Grows with key
        # cardinality only; replaces the former all-records host concat +
        # sort + Python dict.
        kt = {"segs": [], "n": 0}  # [(u64 sorted, keys)], total entries

        partials = []  # folded (h1, h2, v) lane triples

        def keys_equal(a, b):
            if a.dtype != object and b.dtype != object:
                return bool(np.all(a == b))
            return all(x == y for x, y in zip(a, b))

        def merge_segs(a, b):
            """Allocate-once merge of two disjoint sorted (u, k) segments."""
            ua, ka = a
            ub, kb = b
            n = len(ua) + len(ub)
            tgt = np.searchsorted(ua, ub) + np.arange(len(ub))
            ou = np.empty(n, dtype=np.uint64)
            mask = np.ones(n, dtype=bool)
            mask[tgt] = False
            ou[tgt] = ub
            ou[mask] = ua
            if ka.dtype != kb.dtype:
                allk = _concat_cols([ka, kb])
                ka, kb = allk[:len(ka)], allk[len(ka):]
            ok = np.empty(n, dtype=ka.dtype)
            ok[tgt] = kb
            ok[mask] = ka
            return ou, ok

        def merge_table(keys, h1, h2):
            """Fold the window's (hash -> key) pairs into the segment table,
            verifying equal 64-bit hashes always carry equal keys."""
            u = combine64(h1, h2)
            worder = np.argsort(u, kind="stable")
            su = u[worder]
            sk = np.asarray(keys).take(worder)
            # In-window dedup with the collision check on adjacent dups.
            first = np.empty(len(su), dtype=bool)
            first[0] = True
            np.not_equal(su[1:], su[:-1], out=first[1:])
            dup = np.flatnonzero(~first)
            if len(dup) and not keys_equal(sk.take(dup), sk.take(dup - 1)):
                raise _HostPath  # 64-bit collision
            keep = np.flatnonzero(first)
            su = su[keep]
            sk = sk.take(keep)
            # Cross-segment exists check (every segment is consulted; a key
            # lives in exactly one).
            new_mask = np.ones(len(su), dtype=bool)
            for eu, ek in kt["segs"]:
                pos_c = np.minimum(np.searchsorted(eu, su), len(eu) - 1)
                exists = eu[pos_c] == su
                hit = np.flatnonzero(exists & new_mask)
                if len(hit) and not keys_equal(
                        sk.take(hit), ek.take(pos_c[hit])):
                    raise _HostPath  # cross-window 64-bit collision
                new_mask &= ~exists
            idx = np.flatnonzero(new_mask)
            if len(idx):
                kt["segs"].append((su[idx], sk.take(idx)))
                kt["n"] += len(idx)
                while (len(kt["segs"]) > 1
                       and len(kt["segs"][-2][0])
                       <= 2 * len(kt["segs"][-1][0])):
                    b = kt["segs"].pop()
                    a = kt["segs"].pop()
                    kt["segs"].append(merge_segs(a, b))
            if kt["n"] * 80 > acc_budget:
                raise _HostPath  # extreme cardinality: stream on host

        def table_compact():
            """Merge all segments into the single sorted (u, k) table the
            final hash -> key join consumes."""
            while len(kt["segs"]) > 1:
                b = kt["segs"].pop()
                a = kt["segs"].pop()
                kt["segs"].append(merge_segs(a, b))
            if kt["segs"]:
                return kt["segs"][0]
            return np.empty(0, dtype=np.uint64), np.empty(0, dtype=object)

        # Device-resident accumulation state: partials are the raw padded
        # (h1, h2, v, ok) jax arrays from each window's collective fold —
        # they never round-trip through the host; re-folds concatenate and
        # re-run the program in HBM, and only the final result is fetched.
        # Lane safety across windows is tracked host-side (where the window
        # data still is): the running elementwise abs-sum bounds every
        # partial magnitude, and all windows must share one lane dtype.
        acc = {"abs": 0, "dtype": None, "nonneg": True,
               "lane_max": 2 ** 64}

        def compact():
            from .parallel.shuffle import compact_partial

            # compact_partial bounds the padded lanes at the distinct-key
            # count: refold outputs are capacity-padded (~1.5x input,
            # dead rows included), so re-feeding them uncompacted grows
            # the accumulated partial geometrically across rounds.
            f = compact_partial(mesh_keyed_refold(
                mesh, partials, op.kind, nonneg=acc["nonneg"]))
            del partials[:]
            partials.append(f)

        def maybe_compact():
            # Compact by accumulated LANE volume, not partial count:
            # handoff refs are vocabulary-sized (hundreds of tiny
            # partials are cheaper to hold than to re-fold), while the
            # window path's partials are capacity-sized and must not
            # stack past device memory.
            if len(partials) > 1 and (
                    len(partials) >= 256
                    or sum(int(p[0].shape[0]) for p in partials)
                    >= _REFOLD_LANE_CAP):
                compact()

        def flush(win_blocks):
            blk = Block.concat(win_blocks)
            if not len(blk):
                return
            vals = blk.values
            if vals.ndim != 1:
                raise _HostPath  # composite lanes fold on the segment path
            if vals.dtype == np.bool_:
                vals = vals.astype(np.int64)
            if vals.dtype == np.float64 and not x64:
                raise _HostPath
            if vals.dtype.kind in "iu":
                # The lane dtype this window will fold in: int32 with x64
                # off (_lane_safe_values casts), the input dtype otherwise.
                # The running bound must respect the NARROWEST lane used.
                lane_dt = np.dtype(np.int32) if not x64 else vals.dtype
                acc["lane_max"] = min(acc["lane_max"],
                                      int(np.iinfo(lane_dt).max))
                if op.kind == "sum":
                    # Only sums can exceed the element range across windows;
                    # min/max results stay inside the per-window-checked
                    # element range and need no cross-window guard.
                    if x64:
                        # values are unbounded here; a wrapped int64 np-sum
                        # could hide an overflow, so bound with a margined
                        # float64 over-estimate instead.
                        s = float(np.abs(vals.astype(np.float64)).sum())
                        acc["abs"] += s * (1 + 1e-6) + 1
                    else:
                        # per-window lane checks cap |v| at 2^31, so the
                        # int64 window sum (<= 2^58) cannot wrap, and the
                        # running total is an exact Python int
                        acc["abs"] += int(np.abs(
                            vals.astype(np.int64, copy=False)).sum())
                    if acc["abs"] > acc["lane_max"]:
                        raise _HostPath  # cross-window overflow: host exact
                # The scan lowering's -1 sentinel needs SIGNED lanes and
                # nonneg values (mesh_keyed_fold's own gate mirrors this).
                if acc["nonneg"] and (lane_dt.kind != "i" or (
                        len(vals) and int(vals.min()) < 0)):
                    acc["nonneg"] = False
            else:
                acc["nonneg"] = False
            h1, h2 = blk.hashes()
            merge_table(blk.keys, h1, h2)
            try:
                f = mesh_keyed_fold(mesh, h1, h2, vals, op.kind, raw=True)
            except ValueError:
                raise _HostPath
            if acc["dtype"] is None:
                acc["dtype"] = f[2].dtype
            elif f[2].dtype != acc["dtype"]:
                raise _HostPath  # mixed lane dtypes across windows
            partials.append(f)
            maybe_compact()

        _I32 = 2 ** 31 - 1
        _I64 = 2 ** 63 - 1

        def flush_dev(ref):
            """Queue one HBM-resident block for the collective fold
            without any host lane copy: the device lanes ride straight
            into the refold as a raw partial (``ok`` marks the valid
            prefix — handoff refs may carry pow2-padded lanes); the
            exact-key table merges from the ref's HOST-side metadata
            (keys + hashes kept at registration); overflow/nonneg
            bookkeeping uses the registration-time lane_abs/lane_min
            numbers — the same math flush() runs on host values, sourced
            where the host array last existed.  One deterministic final
            refold replaces the former per-ref fold programs, so compile
            buckets stay bounded regardless of ref count or arrival
            order."""
            import jax as _jax

            dv, dh1, dh2 = ref.device_lanes()
            keys, h1, h2 = ref.host_meta()
            lane_dt = np.dtype(dv.dtype)
            if lane_dt.kind in "iu":
                acc["lane_max"] = min(acc["lane_max"],
                                      int(np.iinfo(lane_dt).max))
                if op.kind == "sum":
                    if x64:
                        acc["abs"] += float(ref.lane_abs) * (1 + 1e-6) + 1
                    else:
                        acc["abs"] += int(ref.lane_abs)
                    if acc["abs"] > acc["lane_max"]:
                        raise _HostPath  # cross-window overflow: host exact
                if acc["nonneg"] and (lane_dt.kind != "i"
                                      or ref.lane_min < 0):
                    acc["nonneg"] = False
            else:
                acc["nonneg"] = False
            merge_table(keys, h1, h2)
            if acc["dtype"] is None:
                acc["dtype"] = dv.dtype
            elif dv.dtype != acc["dtype"]:
                raise _HostPath  # mixed lane dtypes across windows
            n_lanes = int(dv.shape[0])
            ok = np.zeros(n_lanes, dtype=np.uint32)
            ok[:len(ref)] = 1
            partials.append((dh1, dh2, dv, _jax.device_put(ok)))
            maybe_compact()

        try:
            win, wbytes = [], 0
            dev_folds = 0
            for ref in refs:
                if getattr(ref, "is_device", False) and len(ref):
                    # HBM-resident map output: fold it where it lives.
                    flush_dev(ref)
                    dev_folds += 1
                    continue
                for w in ref.iter_windows():
                    if not len(w):
                        continue
                    win.append(w)
                    wbytes += w.nbytes()
                    if wbytes >= window_budget:
                        flush(win)
                        win, wbytes = [], 0
            if win:
                flush(win)
            if dev_folds:
                log.info("mesh fold: %d HBM-resident blocks consumed "
                         "on-device", dev_folds)
            if not partials:
                return storage.PartitionSet(self.n_partitions,
                                            hash_routed=True,
                                            hash_sorted=True), 0, 1
            if len(partials) > 1:
                compact()
        except _HostPath:
            log.info("mesh fold: falling back to the host path")
            return None

        # One fetch for the whole reduce: mask the final partial's live
        # rows.  The async refold dispatches materialize here, so this IS
        # the stage's final fold work (the span the host combine path
        # emits at its own final fold).
        with _trace.span("fold", "final-fold"):
            rh1, rh2, rv, rok = partials[0]
            mask = np.asarray(rok) == 1
            fh1 = np.asarray(rh1)[mask]
            fh2 = np.asarray(rh2)[mask]
            fv = np.asarray(rv)[mask]
            # Vectorized hash -> key join against the compacted table
            # (every output hash entered the table with its window).
            tu, tk = table_compact()
            fu = combine64(fh1, fh2)
            idx = np.minimum(np.searchsorted(tu, fu), len(tu) - 1)
            assert bool(np.all(tu[idx] == fu)), "mesh fold lost a key"
            out_keys = tk.take(idx)

        pin = bool(stage.options.get("memory"))
        pset, nrec = self._emit_keyed_fold(out_keys, fv, fh1, fh2, pin)
        self.mesh_folds += 1
        log.info("mesh fold: %d keys folded across %d devices",
                 nrec, len(jax.devices()))
        return pset, nrec, 1

    def _code_exchange_batch(self, batch, op):
        """CAMR-style coded aggregation (settings.exchange_coding): fold
        each destination partition's window blocks into ONE partial under
        the stage's associative op BEFORE they cross the mesh — replicated
        map-side fold work traded for strictly fewer shuffle bytes
        (duplicate keys collapse host-side; arXiv 1901.07418).  Exactness
        gate per partition: integer lanes for sums (float summation order
        would drift ulps), any real numeric lane for min/max; ineligible
        or fold-failing partitions ship raw.  Returns (coded batch,
        raw_bytes, coded_bytes)."""
        by_pid = {}
        raw_bytes = 0
        for s, pid, item in batch:
            blk = (item.get() if isinstance(item, storage.BlockRef)
                   else item)
            raw_bytes += blk.nbytes()
            by_pid.setdefault(pid, []).append((s, blk))
        out = []
        coded_bytes = 0
        for pid in sorted(by_pid):
            items = by_pid[pid]
            blocks = [b for _s, b in items]
            seq0 = min(s for s, _b in items)
            merged = blocks[0] if len(blocks) == 1 else Block.concat(
                blocks)
            vals = merged.values
            kinds = "iu" if op.kind == "sum" else "iuf"
            eligible = vals.ndim == 1 and vals.dtype.kind in kinds
            if eligible:
                try:
                    folded = segment.fold_block(merged, op)
                except Exception:  # exactness fallback: ship raw
                    eligible = False
            if eligible:
                coded_bytes += folded.nbytes()
                out.append((seq0, pid, folded))
            else:
                for s, blk in items:
                    coded_bytes += blk.nbytes()
                    out.append((s, pid, blk))
        return out, raw_bytes, coded_bytes

    def _mesh_exchange_entries(self, entries, target=None, reducer=None):
        """The general shuffle on the mesh (the reference's universal
        DefaultShuffler — base.py:416-433 — as a collective): every input
        partition's blocks cross a budget-scheduled ``all_to_all`` byte
        exchange, streamed in windows bounded by the run budget, with
        partition pid landing on device pid % D.  Joins stay co-partitioned
        because both inputs route identically.  ``target`` is the plan
        layer's shuffle choice for this stage (see ``_exchange_mesh_gate``);
        ``reducer`` (the consuming stage's reducer, when there is exactly
        one input) arms the coded-aggregation pre-fold for sum-combinable
        keyed folds under ``settings.exchange_coding``.
        Returns the exchanged PartitionSets (new refs registered against
        the store), or None when the mesh path is disabled or only one
        device is visible."""
        gate = _exchange_mesh_gate(self.store.budget, target)
        if gate is None:
            return None
        mesh, D, window = gate
        from .parallel import exchange as px

        coding_op = None
        if (settings.exchange_coding_enabled() and len(entries) == 1
                and isinstance(reducer, base.AssocFoldReducer)
                and getattr(reducer.op, "kind", None)
                in ("sum", "min", "max")):
            coding_op = reducer.op

        out_entries = []
        ran_exchange = False
        for pset in entries:
            out = storage.PartitionSet(pset.n_partitions)
            batch, batch_bytes = [], 0
            seq = 0

            def flush():
                nonlocal batch, batch_bytes, ran_exchange
                if not batch:
                    return
                coding_info = None
                if coding_op is not None:
                    coded, raw_b, coded_b = self._code_exchange_batch(
                        batch, coding_op)
                    batch = coded
                    coding_info = {"mode": "camr", "raw_bytes": raw_b,
                                   "coded_bytes": coded_b}
                routed = [
                    (s, s % D, pid,
                     item.get() if isinstance(item, storage.BlockRef)
                     else item)
                    for s, pid, item in batch]
                received, moved = px.mesh_shuffle_blocks(
                    mesh, routed, coding=coding_info)
                if coding_info is not None and not (
                        px.last_info or {}).get("skipped"):
                    # Counted only when the window actually crossed the
                    # mesh: a mitigation-skipped window shuffled zero
                    # bytes, so claiming coded "savings" there would
                    # double-count what windows_skipped already reports.
                    self.coded_exchange["windows"] += 1
                    self.coded_exchange["raw_bytes"] += (
                        coding_info["raw_bytes"])
                    self.coded_exchange["coded_bytes"] += (
                        coding_info["coded_bytes"])
                for pid, blk in received:
                    out.add(pid, self.store.register(blk))
                self.mesh_exchange_bytes += moved
                if px.last_info is not None:
                    self.mesh_exchange_steps += px.last_info["steps"]
                    self.mesh_exchange_peak_inflight = max(
                        self.mesh_exchange_peak_inflight,
                        px.last_info["peak_inflight_bytes"])
                ran_exchange = True
                batch, batch_bytes = [], 0

            def add(pid, item, nbytes):
                nonlocal batch_bytes, seq
                batch.append((seq, pid, item))
                seq += 1
                batch_bytes += nbytes
                if batch_bytes >= window:
                    flush()

            for pid in sorted(pset.parts):
                for ref in pset.parts[pid]:
                    if ref.nbytes <= window:
                        add(pid, ref, ref.nbytes)
                        continue
                    # An over-window block would amplify to a D*D-row buffer
                    # of its own pow2 size; stream it in bounded pieces
                    # instead (consecutive slices of a sorted run stay
                    # sorted runs, and seq order keeps arrival order).
                    piece, pbytes = [], 0
                    for w in ref.iter_windows():
                        piece.append(w)
                        pbytes += w.nbytes()
                        if pbytes >= window:
                            add(pid, Block.concat(piece), pbytes)
                            piece, pbytes = [], 0
                    if piece:
                        add(pid, Block.concat(piece), pbytes)
            flush()
            out_entries.append(out)
        if ran_exchange:
            self.mesh_exchanges += 1
        return out_entries

    def _tiny_assoc_reduce(self, stage, entries):
        """Small-stage fast path for associative folds: fold EVERY partition
        in one vectorized pass over the concatenated refs, then re-split by
        the same hash % P.  Partition identity of each key is unchanged
        (same hash, same P); only the per-partition numpy fixed costs —
        which dominate when partitions hold a few hundred records — are
        collapsed.  Output shape matches the per-partition reducer exactly:
        (k, (k, acc)) records, unordered within a partition (the same
        contract the mesh fold path already ships)."""
        if len(entries) != 1 or not isinstance(stage.reducer,
                                               base.AssocFoldReducer):
            return None
        refs = list(entries[0].all_refs())
        P = self.n_partitions
        pin = bool(stage.options.get("memory"))
        if not refs:
            return storage.PartitionSet(P, hash_routed=True,
                                        hash_sorted=True), 0, 1
        # The one-pass fold materializes every ref at once, so it must stay
        # inside the streaming memory discipline, not just the tiny-stage
        # cutoff.
        limit = settings.small_stage_bytes
        thr = settings.streaming_reduce_threshold
        if thr is None:
            thr = self.store.budget
        # Streamed-edge inputs gate on STAGED bytes: early folds shrink
        # the refs, but this fast path emits a different (hash-order)
        # layout than the per-partition jobs, so the branch decision must
        # match what the staged run would have taken byte-for-byte.
        staged_extra = sum(getattr(entries[0], "pipeline_fold_delta",
                                   {}).values())
        if staged_extra + sum(getattr(r, 'total_bytes', r.nbytes)
                              for r in refs) > min(limit, thr):
            return None
        merged = Block.concat([r.get() for r in refs])
        if not len(merged):
            return storage.PartitionSet(P, hash_routed=True,
                                        hash_sorted=True), 0, 1
        folded = segment.fold_sorted(
            segment.sort_and_group(merged), stage.reducer.op)
        h1, h2 = folded.hashes()
        pset, nrec = self._emit_keyed_fold(folded.keys, folded.values,
                                           h1, h2, pin)
        return pset, nrec, 1

    def _emit_keyed_fold(self, keys, vals, h1, h2, pin):
        """Register a keyed fold result as a stage-output PartitionSet in
        the reduce-output contract: (k, (k, acc)) records (KeyedReduce
        shape), np.generic values unwrapped to Python scalars, split by the
        engine hash % P.  Shared by the mesh fold and tiny-fold fast paths
        so the contract lives in exactly one place."""
        from .blocks import pylist

        P = self.n_partitions
        n = len(keys)
        kl = pylist(keys) if isinstance(keys, np.ndarray) else list(keys)
        vl = pylist(vals) if isinstance(vals, np.ndarray) else list(vals)
        vcol = np.empty(n, dtype=object)
        for i in range(n):
            vcol[i] = (kl[i], vl[i])
        out_blk = Block(keys, vcol, h1, h2)
        # Hash-routed by construction (split below); the sub-blocks keep the
        # fold's output order, which is NOT a (h1, h2)-sorted run — consumers
        # that need sorted runs (a following reduce) re-establish them in the
        # copy stage the alias gate forces.
        pset = storage.PartitionSet(P, hash_routed=True)
        nrec = 0
        for pid, sub in out_blk.split_by_partition(P).items():
            nrec += len(sub)
            pset.add(pid, self.store.register(sub, pin=pin))
        return pset, nrec

    def run_reduce(self, stage_id, stage, env):
        entries = [env[s] for s in stage.inputs]
        for e in entries:
            assert isinstance(e, storage.PartitionSet), (
                "reduce inputs must be materialized partitions; the DSL "
                "checkpoints before grouping")
        fast = self._mesh_reduce(stage, entries)
        if fast is not None:
            return fast
        fast = self._tiny_assoc_reduce(stage, entries)
        if fast is not None:
            return fast
        exchanged = self._mesh_exchange_entries(
            entries, target=self._shuffle_targets.get(stage_id),
            reducer=stage.reducer)
        if exchanged is not None:
            entries = exchanged
        P = self.n_partitions
        pin = bool(stage.options.get("memory"))

        threshold = settings.streaming_reduce_threshold
        if threshold is None:
            threshold = self.store.budget
        # The streaming merge yields groups in hash order, not key order —
        # safe for per-group reducers (Reduce/KeyedReduce/AssocFoldReducer,
        # where each group is independent), but Stream/BlockReducers observe
        # the group sequence directly, so they always get the key-ordered
        # materialized view.
        order_insensitive = isinstance(
            stage.reducer, (base.Reduce, base.AssocFoldReducer))

        joinable = isinstance(
            stage.reducer, (base.KeyedInnerJoin, base.KeyedLeftJoin,
                            base.KeyedOuterJoin))

        def _streaming_assoc_fold(refs, reducer):
            """Over-budget associative fold, vectorized: fold each spill
            window as it streams and re-compact partials — the working set is
            one accumulator of *distinct keys*, not the partition's records
            (the reduce-side mirror of the map-side _PARTIAL_FANIN combine).
            Returns None (caller falls back to the per-record stream) if the
            accumulator itself outgrows the threshold (extreme cardinality).
            """
            op = reducer.op
            partials = []

            def compact():
                merged = segment.fold_block(Block.concat(partials), op)
                del partials[:]
                partials.append(merged)
                return merged.nbytes()

            for ref in refs:
                for window in ref.iter_windows():
                    if not len(window):
                        continue
                    partials.append(segment.fold_block(window, op))
                    if len(partials) >= _PARTIAL_FANIN:
                        if compact() > threshold:
                            return None
            if not partials:
                return iter(())
            self.streamed_assoc_folds += 1
            final = segment.fold_sorted(
                segment.sort_and_group(Block.concat(partials)), op)
            gkeys = final.keys
            try:
                order = np.argsort(gkeys, kind="stable")
            except TypeError:
                order = np.arange(len(final))

            def emit():
                vals = final.values
                for gi in order:
                    k = gkeys[gi]
                    v = vals[gi]
                    k = k.item() if isinstance(k, np.generic) else k
                    v = v.item() if isinstance(v, np.generic) else v
                    yield k, (k, v)

            return emit()

        def job(pid):
            if joinable and len(entries) == 2:
                sizes = [sum(r.total_bytes for r in pset.refs(pid))
                         for pset in entries]
                if sum(sizes) > threshold:
                    # Over-budget join partition: hash-ordered streaming
                    # merge join — memory bound is the largest single
                    # join-key group, not the partition.
                    log.info(
                        "partition %d join (%.1f MB) exceeds the streaming "
                        "threshold: merging by hash order", pid,
                        sum(sizes) / 1e6)
                    lview = base.StreamingGroupedView(entries[0].refs(pid))
                    rview = base.StreamingGroupedView(entries[1].refs(pid))
                    reducer = _clone_op(stage.reducer)
                    builder = BlockBuilder(settings.batch_size)
                    refs_out = []
                    for k, v in base.streaming_merge_join(lview, rview,
                                                          reducer):
                        blk = builder.add(k, v)
                        if blk is not None:
                            refs_out.append(
                                self.store.register(blk, pin=pin))
                    blk = builder.flush()
                    if blk is not None:
                        refs_out.append(self.store.register(blk, pin=pin))
                    return pid, refs_out
            record_stream = None
            if len(entries) == 1:
                prefs = entries[0].refs(pid)
                part_bytes = sum(r.total_bytes for r in prefs)
                if (part_bytes > threshold
                        and isinstance(stage.reducer, base.AssocFoldReducer)
                        and stage.reducer.op.kind is not None):
                    record_stream = _streaming_assoc_fold(
                        prefs, stage.reducer)

            if record_stream is None:
                views = []
                for pset in entries:
                    refs = pset.refs(pid)
                    part_bytes = sum(r.total_bytes for r in refs)
                    if (len(entries) == 1 and order_insensitive
                            and part_bytes > threshold):
                        # Out-of-core partition: stream a k-way merge over
                        # the hash-sorted runs — one window per run resident
                        # — instead of materializing the whole partition.
                        # (Over-budget joins were handled above; assoc folds
                        # with recognized ops took the vectorized accumulator
                        # unless cardinality blew it; Stream/BlockReducers
                        # still materialize.)
                        log.info(
                            "partition %d (%.1f MB) exceeds the streaming "
                            "threshold: groups will stream in hash order",
                            pid, part_bytes / 1e6)
                        views.append(base.StreamingGroupedView(refs))
                    else:
                        views.append(base.GroupedView(
                            [ref.get() for ref in refs]))
                reducer = _clone_op(stage.reducer)
                record_stream = reducer.reduce(*views)

            builder = BlockBuilder(settings.batch_size)
            refs = []
            prof = _profile.active()
            if prof is None:
                # The profiler-off hot loop stays increment-free (the
                # one-None-check-per-job contract).
                for k, v in record_stream:
                    blk = builder.add(k, v)
                    if blk is not None:
                        refs.append(self.store.register(blk, pin=pin))
            else:
                # Whole-stream attribution (a reducer doesn't decompose
                # per op): grouping + the user's reduce + re-register.
                t0p = time.perf_counter()
                nrec_out = 0
                for k, v in record_stream:
                    nrec_out += 1
                    blk = builder.add(k, v)
                    if blk is not None:
                        refs.append(self.store.register(blk, pin=pin))
                prof.op_add(
                    "reduce:" + _profile.op_label(stage.reducer),
                    time.perf_counter() - t0p, records=nrec_out)
            blk = builder.flush()
            if blk is not None:
                refs.append(self.store.register(blk, pin=pin))
            return pid, refs

        n_reducers = stage.options.get("n_reducers", self.n_reducers)
        try:
            results = self._pool_run(job, list(range(P)), n_reducers,
                                     label="reduce",
                                     speculative=self._speculation_ok(stage))
        finally:
            if exchanged is not None:
                # The exchanged copies are intermediates private to this
                # reduce; the originals in env still own the stage output
                # lifecycle.  finally: a reducer exception must not leak a
                # duplicate of the stage input against the budget.
                for e in exchanged:
                    e.delete(self.store)

        pset = storage.PartitionSet(P)
        nrec = 0
        for pid, refs in results:
            for ref in refs:
                nrec += len(ref)
                pset.add(pid, ref)
        return pset, nrec, P

    # -- sink --------------------------------------------------------------
    def run_sink(self, stage_id, stage, env):
        entries = [env[s] for s in stage.inputs]
        chunks = self._as_chunks(entries[0])
        # Same tiny-input collapse as run_map: sink chunking (one part file
        # per chunk) is mechanical, and the sinker is always a fused record
        # stream (dampr.py sink()).
        if (len(chunks) > 1
                and isinstance(entries[0], storage.PartitionSet)
                and type(stage.sinker) in (base.Map, base.ComposedMapper)):
            refs = list(entries[0].all_refs())
            if sum(getattr(r, 'total_bytes', r.nbytes)
                   for r in refs) <= settings.small_stage_bytes:
                chunks = [BlockDataset(refs)]
        os.makedirs(stage.path, exist_ok=True)

        def job(args):
            i, chunk = args
            mapper = _clone_op(stage.sinker)
            part = os.path.join(stage.path, "part-{}".format(i))
            n = 0
            prof = _profile.active()
            t0p = time.perf_counter() if prof is not None else 0.0
            with open(part, "w", encoding="utf-8") as f:
                for _k, v in mapper.map(chunk):
                    f.write("{}\n".format(v))
                    n += 1
            if prof is not None:
                # Sink chains don't decompose per op (fused generators
                # interleave with the writes); one whole-stream label
                # keeps the stage's coverage honest.
                prof.op_add("sink:" + _profile.op_label(mapper),
                            time.perf_counter() - t0p, records=n)
            return part, n

        n_maps = stage.options.get("n_maps", self.n_maps)
        results = self._pool_run(job, list(enumerate(chunks)), n_maps,
                                 label="sink", speculative=False)
        paths = [p for p, _ in results]
        nrec = sum(n for _, n in results)
        return _SinkOutput(paths), nrec, len(chunks)

    def _reduce_consumes(self, output, _seen=None):
        """Does a GReduce consume ``output`` — directly, or through
        identity checkpoint stages (which alias or copy it forward
        unchanged)?  Run-mode planning (sorted runs vs hash fan-out) and
        the alias provenance gate share this transitive view so they
        cannot disagree about what a downstream reduce will need."""
        seen = _seen if _seen is not None else set()
        if output in seen:
            return False
        seen.add(output)
        for s in self.graph.stages:
            if output not in s.inputs:
                continue
            if isinstance(s, GReduce):
                return True
            if (isinstance(s, GMap)
                    and type(s.mapper) is base.Map
                    and s.mapper.mapper is base._identity
                    and s.combiner is None
                    and "binop" not in s.options
                    and self._reduce_consumes(s.output, seen)):
                return True
        return False

    def _alias_provenance_ok(self, stage, src):
        """May an identity checkpoint alias ``src`` instead of running the
        copy stage?  The copy stage it elides would hash-route every record
        (split_by_partition) and register hash-sorted runs — invariants a
        consuming GReduce depends on for partition-local grouping and the
        over-budget streaming merge.  So the alias stands only when no
        reduce consumes the output (directly or through further identity
        checkpoints), or the input already carries both invariants by
        construction (map-stage outputs).  Reduce outputs are registered
        under the reduce job's pid with whatever keys the reducer emitted
        — e.g. ``X.partition_reduce(f).partition_reduce(g)`` aliasing f's
        output would leave g grouping each key only within f's job
        partitions: silently wrong results (ADVICE round 5)."""
        if not self._reduce_consumes(stage.output):
            return True
        return src.hash_routed and src.hash_sorted

    # -- main walk ---------------------------------------------------------
    def _register_gauges(self):
        """Install the load-bearing pull gauges once per run: the hot
        paths whose state they expose pay nothing — the background
        sampler evaluates these callbacks on its cadence."""
        from .ops import devtime

        m = self.metrics
        sto = self.store
        m.register_gauge("store.resident_bytes",
                         lambda: sto._resident_bytes)
        m.register_gauge(
            "store.budget_occupancy",
            lambda: (sto._resident_bytes / sto.budget) if sto.budget
            else 0.0)
        m.register_gauge("store.overlap_bytes", lambda: sto._overlap_bytes)
        m.register_gauge("store.hbm_bytes", lambda: sto._dev_bytes)
        m.register_gauge("store.spilled_bytes", lambda: sto.spilled_bytes)

        def _writer(attr):
            w = sto._writer
            return 0 if w is None else getattr(w, attr)

        m.register_gauge("writer.queue_depth",
                         lambda: _writer("_outstanding"))
        m.register_gauge("writer.inflight_bytes",
                         lambda: _writer("inflight_bytes"))
        m.register_gauge("overlap.live_slots", devtime.live_slots)
        m.register_gauge("overlap.stalled_slots", devtime.stalled_slots)
        m.register_gauge(
            "run.active_jobs",
            lambda: m.counters.get("run.jobs_started", 0)
            - m.counters.get("run.jobs_done", 0))

    def _start_obs(self):
        """Run-scoped observability setup: tracer (settings.trace),
        flight recorder (tracing OR metrics on), metrics registry +
        sampler (effective_metrics_interval_ms > 0), progress reporter
        (settings.progress).  Returns the flight recorder (the failure
        path flushes it)."""
        from .obs import flightrec as _flightrec

        interval = settings.effective_metrics_interval_ms()
        rec = None
        if settings.trace or interval > 0:
            # A crashdump describes the LATEST run under this name: a
            # stale one from an earlier failure must not keep failing
            # dampr-tpu-stats after the rerun succeeds.
            _flightrec.clear_stale(self.name)
        if settings.flight_recorder_events > 0 and (settings.trace
                                                    or interval > 0):
            rec = _flightrec.FlightRecorder(
                self.name, settings.flight_recorder_events)
            self.flightrec = rec
            _flightrec.start(rec)
        lvl = settings.effective_log_level()
        if lvl or rec is not None:
            # Structured log stream: on-disk events.jsonl when a level is
            # in force (explicit DAMPR_TPU_LOG, or the traced-run "info"
            # default), recorder-only otherwise — an unstreamed metered
            # run still gets a WARN+ tail in its crashdump.  Starts
            # before the remaining obs pieces so THEIR warnings (port
            # fallback, bind failure) land as coded events too.
            from .parallel.mesh import rank_info

            path = None
            if lvl and settings.log_events_max > 0:
                from .obs import export as _export

                tdir = _export.run_trace_dir(self.name)
                os.makedirs(tdir, exist_ok=True)
                path = os.path.join(tdir, _obslog.FILE)
            self.logstream = _obslog.LogStream(
                self.name, rank=rank_info()[0], level=lvl or "warn",
                path=path, recorder=rec)
            _obslog.start(self.logstream)
            _obslog.info("run-start", "run %s started", self.name,
                         partitions=getattr(self, "n_partitions", None))
        if settings.trace:
            # Run-scoped engine timeline.  The tracer is process-global
            # while active (instrumentation sites are free functions);
            # concurrent traced runs in one process would interleave spans
            # into the innermost tracer — run-level metrics stay exact
            # regardless (they come from this runner's own counters).
            self.tracer = _trace.Tracer(self.name)
            self.tracer.recorder = rec
            _trace.start(self.tracer)
        if settings.profile:
            # Per-operator attribution (obs.profile): passive — no
            # thread; hot sites hoist the None-check to one per job.
            self.profiler = _profile.Profiler(self.name)
            _profile.start(self.profiler)
        if settings.mitigate_enabled():
            # Straggler mitigation controller: live skew -> action.
            # Every rank of a process group builds one and feeds it the
            # same shared observations, so collective decisions agree.
            self._mitigation = _mitigate.MitigationController(self.name)
            _mitigate.start(self._mitigation)
        if interval > 0:
            from .obs.metrics import Metrics
            from .obs.sampler import Sampler

            self.metrics = Metrics(self.name)
            if self.tracer is not None:
                # One clock: counter events and span events share the
                # tracer's epoch inside trace.json.
                self.metrics.epoch = self.tracer.epoch
            self._register_gauges()
            _metrics.start(self.metrics)
            self._sampler = Sampler(self.metrics, interval, recorder=rec)
            self._sampler.start()
            if settings.progress:
                from .obs.progress import ProgressReporter

                self._progress = ProgressReporter(
                    self.metrics, lambda: dict(self._status),
                    settings.progress_interval_ms)
                self._progress.start()
            if settings.metrics_port > 0:
                # Live metrics endpoint: per-rank /metrics + /healthz on
                # metrics_port + process_id (co-located ranks never
                # collide).  Best-effort — a busy port degrades the
                # endpoint, never the run.
                from .obs import serve as _serve

                self._metrics_server = _serve.start_server(
                    settings.metrics_port, run_name=self.name)
        # Route-matrix epoch: the exchange module's counters are
        # process-cumulative; remember where they stood so finalize can
        # attribute only this run's bytes.
        try:
            from .parallel import exchange as px

            self._exchange_snapshot = (
                dict(px.sent_bytes_per_device),
                dict(px.received_bytes_per_device),
                dict(px.pair_bytes_per_route),
                {"codec_raw": px.codec_raw_bytes,
                 "codec_wire": px.codec_wire_bytes,
                 "pack_seconds": px.pack_seconds_total,
                 "pack_hidden": px.pack_hidden_seconds_total})
        except Exception:
            self._exchange_snapshot = None
        return rec

    def _stop_obs(self):
        from .obs import flightrec as _flightrec

        if self._progress is not None:
            self._progress.stop()
        if self._sampler is not None:
            self._sampler.stop()
        if self.metrics is not None:
            _metrics.stop(self.metrics)
        if self.tracer is not None:
            _trace.stop(self.tracer)
        if self.profiler is not None:
            _profile.stop(self.profiler)
        if self.flightrec is not None:
            _flightrec.stop(self.flightrec)
        if self._mitigation is not None:
            _mitigate.stop(self._mitigation)
        if self._metrics_server is not None:
            srv = self._metrics_server
            if srv.port is not None:
                # Survives the teardown: finalize records the LIVE port
                # (fallback-shifted or not) in stats()["endpoint"].
                self._endpoint_info = {
                    "port": srv.port,
                    "requested": (srv.base_port + srv.rank
                                  if srv.base_port > 0 else srv.base_port),
                    "fallback": srv.fallback,
                }
            srv.stop()
            self._metrics_server = None

    def _install_sigterm(self):
        """Raise-on-SIGTERM while a run is in flight, so an external kill
        walks the same BaseException path as KeyboardInterrupt — flight
        recorder flush, spill-writer abort, nonzero exit — instead of
        dying with no crash artifact.  Only from the main thread (signal
        API constraint) and only when no application handler is already
        installed; returns a restore closure."""
        import signal

        if threading.current_thread() is not threading.main_thread():
            return None
        try:
            prev = signal.getsignal(signal.SIGTERM)
            if prev is not signal.SIG_DFL:
                # The application owns SIGTERM (a Python handler, SIG_IGN,
                # or — getsignal() returning None — a handler installed
                # by non-Python code): never clobber it.
                return None

            def _on_term(signum, frame):
                raise SystemExit(143)  # 128 + SIGTERM, shell convention

            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            return None
        return lambda: signal.signal(signal.SIGTERM, prev)

    def run(self, outputs, cleanup=True):
        from . import plan as _plan
        from .ops import devtime

        # Optimize the stage list for the requested outputs (no-op when
        # the DSL already applied a plan, or settings.optimize is off —
        # the report records either way).  Before obs setup: stage counts
        # and resume fingerprints must see the final graph.
        _plan.apply_to_runner(self, outputs)
        # Pre-flight dispatch check (analyze.validate): on a multi-rank
        # deployment an unpicklable UDF capture WILL fail at a process
        # boundary (checkpoint manifests, quarantine audit lines, the
        # exchange's pickled lanes) — fail here with a diagnostic naming
        # the stage, the UDF, and the closure variable instead of a raw
        # PicklingError traceback from deep inside the dispatch.
        if settings.analyze:
            from .parallel.mesh import rank_info

            nproc = rank_info()[1]
            if nproc > 1:
                from .analyze import validate as _av

                _av.preflight_dispatch_check(self.graph, nproc)
        # Fault plan (settings.faults): a fresh per-run schedule so chaos
        # runs replay identically; the counter epoch scopes the
        # stats()["faults"] section to THIS run.
        _faults.configure_for_run()
        self._fault_snapshot = _faults.counters_snapshot()
        _faults.set_context(run=self.name)
        restore_sigterm = self._install_sigterm()
        wall_start = time.time()
        epoch = devtime.epoch()
        rec = self._start_obs()
        try:
            if settings.profile_dir:
                import jax

                with jax.profiler.trace(settings.profile_dir):
                    return self._run(outputs, cleanup)
            return self._run(outputs, cleanup)
        except BaseException as e:
            # The flight recorder's whole reason to exist: a dying run —
            # stage exception, KeyboardInterrupt, SIGTERM-raised exit —
            # leaves a bounded timeline tail with the last gauge samples
            # (writer-pool queue state included) instead of nothing.
            self._run_failed = True
            if self.logstream is not None:
                # Terminal structured record BEFORE the crashdump flush,
                # so the dump's log tail names the death.  Direct emit
                # (not module error()): the exception is re-raised — a
                # duplicate stdlib error line here would be noise.
                self.logstream.emit(
                    "error", "run-failed",
                    "run {} failed: {}: {}".format(
                        self.name, type(e).__name__, str(e)[:500]),
                    data={"exception": type(e).__name__})
            if rec is not None:
                if self._sampler is not None:
                    # One last snapshot so the dump's final samples show
                    # the state at death, not the previous cadence tick.
                    self._sampler.stop()
                rec.flush("run-failed", e)
            raise
        finally:
            if restore_sigterm is not None:
                try:
                    restore_sigterm()
                except (ValueError, OSError):
                    pass
            _faults.set_context(run=None, stage=None)
            self._stop_obs()
            try:
                # Built on failure too: a partial timeline + stage stats
                # is exactly what a crashed run's postmortem needs.
                self._finalize_obs(wall_start, time.time() - wall_start,
                                   devtime.delta(epoch))
            except Exception:
                log.warning("stats/trace finalize failed", exc_info=True)
            finally:
                # The structured stream outlives _stop_obs so finalize
                # can stamp run-finish; close it last, no matter what.
                if self.logstream is not None:
                    _obslog.stop(self.logstream)
                    self.logstream = None

    def _exchange_deltas(self):
        """THIS run's per-device sent/received bytes and (src, dst)
        device-route matrix: the exchange module's cumulative counters
        minus the snapshot taken at run start.  None when nothing moved
        (the section stays compact for host-only runs)."""
        if self._exchange_snapshot is None:
            return None
        try:
            from .parallel import exchange as px
        except Exception:
            return None
        sent0, recv0, pair0, sc0 = self._exchange_snapshot

        def delta(cur, base):
            out = {}
            for k, v in cur.items():
                d = v - base.get(k, 0)
                if d > 0:
                    out[k] = d
            return out

        sent = delta(px.sent_bytes_per_device, sent0)
        recv = delta(px.received_bytes_per_device, recv0)
        pair = delta(px.pair_bytes_per_route, pair0)
        if not (sent or recv or pair):
            return None
        section = {
            "sent_per_device": {str(k): v for k, v in sorted(sent.items())},
            "received_per_device": {str(k): v
                                    for k, v in sorted(recv.items())},
            # JSON-safe route triples [src_device, dst_device, bytes]
            "routes": [[s, d, n] for (s, d), n in sorted(pair.items())],
        }
        raw = px.codec_raw_bytes - sc0["codec_raw"]
        wire = px.codec_wire_bytes - sc0["codec_wire"]
        if raw > 0:
            # Per-route payload codec evidence (settings.exchange_codec):
            # pre-compression bytes vs wire bytes this run.
            section["codec"] = {
                "raw_bytes": raw, "wire_bytes": wire,
                "savings_fraction": round(1.0 - wire / float(raw), 4)}
        packed = px.pack_seconds_total - sc0["pack_seconds"]
        hidden = px.pack_hidden_seconds_total - sc0["pack_hidden"]
        if packed > 1e-9:
            # Double-buffered schedule evidence: how much of the host
            # pack time hid behind in-flight collectives this run.
            section["overlap"] = {
                "pack_seconds": round(packed, 4),
                "hidden_seconds": round(hidden, 4),
                "hidden_fraction": round(hidden / packed, 4)}
        return section

    def _faults_section(self):
        """The per-run ``stats()["faults"]`` payload: this run's share of
        the process-cumulative retry/injection counters, plus quarantine
        and backoff totals."""
        injected, io_retries, io_backoff = _faults.counters_delta(
            self._fault_snapshot)
        q = self._quarantine
        plan = _faults.active()
        section = {
            "enabled": plan is not None,
            "job_retries": self.retries_total,
            "io_retries": dict(io_retries),
            "retries": self.retries_total + sum(io_retries.values()),
            # Job-loop backoff plus the IO layer's in-place retry sleeps
            # — an IO-only retry storm must show its cost here.
            "backoff_seconds": round(self._backoff_seconds + io_backoff, 4),
            "quarantined": q.count if q is not None else 0,
            "max_quarantined": settings.max_quarantined,
        }
        if q is not None and q.count:
            section["quarantine_file"] = q.path
        if plan is not None:
            section["plan"] = plan.spec
            section["injected"] = dict(injected)
        return section

    def _pipeline_section(self):
        """The per-run ``stats()["pipeline"]`` payload: plan-time edge
        decisions (from the plan report) plus the runtime folder/chain
        counters.  overlap_fraction is the share of streamed-consumer
        seconds that ran WHILE the producing stage's pool was still
        busy — the wall-clock the pipelining actually hid."""
        ps = self._pipeline_stats
        rep = ((self.plan_report or {}).get("pipeline") or {})
        fold_s = ps["fold_seconds"]
        return {
            "enabled": settings.pipeline_enabled(),
            "edges_streamed": rep.get("streamed", 0),
            "edges_barrier": rep.get("barriers", 0),
            "executed": ps["executed"],
            "degraded": ps["degraded"],
            "published": ps["published"],
            "early_folded_blocks": ps["early_folded_blocks"],
            "bytes_in": ps["bytes_in"],
            "bytes_out": ps["bytes_out"],
            "fold_seconds": round(fold_s, 4),
            "overlap_seconds": round(ps["overlap_seconds"], 4),
            "overlap_fraction": (round(ps["overlap_seconds"] / fold_s, 4)
                                 if fold_s > 1e-9 else 0.0),
            "stall_seconds": round(ps["stall_seconds"], 4),
            "queue_peak_bytes": ps["queue_peak_bytes"],
            "queue_depth_series": [[sid, round(t, 4), b]
                                   for sid, t, b
                                   in ps["queue_depth_series"]],
        }

    def _finalize_obs(self, wall_start, wall, dev):
        """Build the per-run summary (the stats.json payload) and, when
        tracing, persist trace.json + stats.json under the run's trace
        directory.  The summary is always built — it is how ``StageStats``
        reaches users (ValueEmitter.stats()); the files are written only
        for traced runs so untraced test/tool runs leave no litter."""
        from .obs import export as _export

        sto = self.store
        stages = [s.as_dict() for s in self.stats]
        summary = {
            "schema": _export.STATS_SCHEMA,
            "run": self.name,
            # Rank identity on every artifact: which process of how many
            # produced this summary (plus the clock-handshake anchor when
            # the process group ran one) — the key obs.fleet merges on.
            "process": _export.process_section(),
            "started_at": round(wall_start, 3),
            "wall_seconds": round(wall, 4),
            "n_partitions": self.n_partitions,
            "stages": stages,
            "totals": {
                "records_out": sum(s["records_out"] for s in stages),
                "bytes_out": sum(s["bytes_out"] for s in stages),
                "spill_bytes": sum(s["spill_bytes"] for s in stages),
            },
            "devtime": {k: round(v, 4) for k, v in dev.items()},
            "overlap": {
                "windows": settings.overlap_windows,
                "stall_fraction": (round(dev.get("codec_wait", 0.0) / wall,
                                         4) if wall > 0 else 0.0),
                "peak_bytes": sto.overlap_peak_bytes,
            },
            # Spill I/O shape (dampr_tpu.io): post-codec disk bandwidth on
            # both sides plus the fold-side stall on writer backpressure /
            # not-yet-prefetched frames — the numbers the async spill
            # subsystem moves (seconds are thread-seconds on the writer/
            # reader pools; io_wait_fraction is against run wall time).
            "io": {
                "spill_write_bytes": sto.spill_disk_bytes,
                "spill_write_seconds": round(sto.spill_write_seconds, 4),
                "spill_write_mbps": (
                    round(sto.spill_disk_bytes / 1e6
                          / sto.spill_write_seconds, 2)
                    if sto.spill_write_seconds > 1e-9 else 0.0),
                "spill_read_bytes": sto.spill_read_bytes,
                "spill_read_seconds": round(sto.spill_read_seconds, 4),
                "spill_read_mbps": (
                    round(sto.spill_read_bytes / 1e6
                          / sto.spill_read_seconds, 2)
                    if sto.spill_read_seconds > 1e-9 else 0.0),
                "io_wait_seconds": round(sto.io_wait_seconds, 4),
                "io_wait_fraction": (round(sto.io_wait_seconds / wall, 4)
                                     if wall > 0 else 0.0),
                # fold-side only (writer backpressure): the stall the
                # background writer pool exists to eliminate; read-side
                # prefetch waits are the difference to the totals above.
                "io_wait_write_seconds": round(sto.io_wait_write_seconds, 4),
                "io_wait_write_fraction": (
                    round(sto.io_wait_write_seconds / wall, 4)
                    if wall > 0 else 0.0),
                "writer_threads": settings.spill_write_threads,
                "read_prefetch": settings.spill_read_prefetch,
                "inflight_peak_bytes": sto.spill_inflight_peak_bytes,
                "writer_queue_peak": sto.spill_queue_peak,
            },
            "store": {
                "budget": sto.budget,
                "spill_count": sto.spill_count,
                "spilled_bytes": sto.spilled_bytes,
                "merge_gens": sto.merge_gens,
                "merge_gen_bytes": sto.merge_gen_bytes,
                "h2d_bytes": sto.h2d_bytes,
                "d2h_bytes": sto.d2h_bytes,
                "hbm_offloads": sto.hbm_offloads,
                "hbm_peak_bytes": sto.hbm_peak_bytes,
                "overlap_peak_bytes": sto.overlap_peak_bytes,
            },
            "mesh": {
                "folds": self.mesh_folds,
                "exchanges": self.mesh_exchanges,
                "exchange_bytes": self.mesh_exchange_bytes,
                # The chunked-collective shape of this run's exchanges:
                # schedule steps executed, the modeled per-step in-flight
                # high-water mark (parallel.replan.step_inflight_bytes),
                # and the budget it was planned under.  mesh_stages is
                # how many redistribution stages the plan routed here.
                "exchange": {
                    "bytes": self.mesh_exchange_bytes,
                    "steps": self.mesh_exchange_steps,
                    "peak_inflight_bytes": self.mesh_exchange_peak_inflight,
                    "hbm_budget": settings.exchange_hbm_budget,
                    "mesh_stages": ((self.plan_report or {}).get("shuffle")
                                    or {}).get("mesh_stages", 0),
                },
            },
        }
        ex_delta = self._exchange_deltas()
        if ex_delta is not None:
            summary["mesh"]["exchange"].update(ex_delta)
        if self.coded_exchange["windows"]:
            # Coded-aggregation evidence: what the CAMR pre-fold traded
            # (replicated map-side fold work) for (shuffle bytes).
            ce = dict(self.coded_exchange)
            ce["mode"] = str(settings.exchange_coding)
            if ce["raw_bytes"]:
                ce["savings_fraction"] = round(
                    1.0 - ce["coded_bytes"] / float(ce["raw_bytes"]), 4)
            summary["mesh"]["exchange"]["coding"] = ce
        summary.update({
            # Device execution: run-wide device counters — device_fraction
            # is thread-seconds inside ANY jitted kernel (lowered programs,
            # segment folds, the hash lexsort, mesh collectives) over wall,
            # and h2d/d2h aggregate the lowered-program feed/drain WITH the
            # HBM tier's puts/fetches.  device_stages is the
            # lowering-specific signal: how many stages the plan placed on
            # device this run.
            "device": {
                "device_fraction": (round(dev.get("device", 0.0) / wall, 4)
                                    if wall > 0 else 0.0),
                "device_seconds": round(dev.get("device", 0.0), 4),
                "h2d_bytes": sto.h2d_bytes,
                "d2h_bytes": sto.d2h_bytes,
                "device_stages": (self.plan_report or {}).get(
                    "device_stages", 0),
                "lowered": bool(((self.plan_report or {}).get("lowering")
                                 or {}).get("enabled")),
                # Cross-stage handoff evidence: device bytes registered
                # without a host round-trip, drain bytes the table
                # programs never fetched, edges the plan marked
                # handoff="device", and runtime degrades back to spill.
                "handoff_bytes": sto.handoff_bytes,
                "d2h_avoided_bytes": sto.d2h_avoided_bytes,
                "handoff_edges": (self.plan_report or {}).get(
                    "handoff_edges", 0),
                "handoff_degrades": sto.handoff_degrades,
            },
            "streamed_assoc_folds": self.streamed_assoc_folds,
            # Barrier-free pipelining evidence (docs/pipeline.md):
            # streamed-edge decisions, early-fold/chain runtime counters,
            # and the overlap the dissolved barriers actually bought.
            "pipeline": self._pipeline_section(),
            "retries": self.retries_total,
            # Failure-recovery summary (dampr_tpu.faults): classified
            # retries absorbed at every layer (job re-executions + the IO
            # layer's in-place transient retries), quarantine state, and
            # injection counts when a chaos plan was active.  "retries"
            # is the headline total the chaos gates assert on.
            "faults": self._faults_section(),
            # The logical plan that executed: stages before/after the
            # optimizer, rules fired, adaptive sizing decisions, and the
            # stage shapes the NEXT run's cost layer matches against.
            "plan": self.plan_report or {"enabled": False},
            "trace_file": None,
            "stats_file": None,
        })
        if self._reuse_summary is not None:
            # Cross-run cache evidence (plan/reuse.py): hits, bytes
            # mounted/published, incremental merges, recompute
            # fallbacks, and the per-stage decision list — what the
            # reuse-smoke CI leg and the doctor findings read.
            summary["reuse"] = self._reuse_summary
        if self._mitigation is not None:
            # What the skew signal made the engine DO: speculative wins,
            # stolen partitions, skipped collective windows, sticky
            # down-weights.  Mirrored into the plan report (the
            # mitigation is a runtime plan change) and — on merged
            # multi-process runs — into stats()["fleet"]["mitigation"].
            mit = self._mitigation.summary()
            summary["mitigation"] = mit
            plan_sec = summary.get("plan")
            if isinstance(plan_sec, dict):
                plan_sec["mitigation"] = {
                    "engagements": mit["engagements"],
                    "disengagements": mit["disengagements"],
                    "windows_skipped": mit["windows_skipped"],
                    "speculative_wins": mit["speculative_wins"],
                    "stolen_partitions": mit["stolen_partitions"],
                    "downweighted_ranks": mit["downweighted_ranks"],
                }
        if self.metrics is not None:
            # Counters, gauge peaks/lasts, histogram summaries, and the
            # sampler's self-accounting (samples, series drops, the
            # overhead self-metric) — the metrics plane measuring itself.
            summary["metrics"] = self.metrics.summary()
        if self.profiler is not None:
            # Per-operator attribution: which of the fused ops the stage
            # time went to, device sub-phases, per-stage coverage.
            summary["profile"] = self.profiler.summary(
                {s.stage_id: s.seconds for s in self.stats})
        if self.flightrec is not None and self.flightrec.path:
            summary["crashdump_file"] = self.flightrec.path
        if self.logstream is not None:
            if not self._run_failed:
                self.logstream.emit(
                    "info", "run-finish",
                    "run {} finished in {:.3f}s".format(self.name, wall),
                    data={"wall_seconds": round(wall, 3)})
            # Where the postmortem log lives + how much of it survived
            # the bound — stats.json's pointer into events.jsonl.
            summary["log"] = self.logstream.summary()
        if self._endpoint_info is not None:
            # The /metrics port this rank ACTUALLY served on (fallback-
            # shifted when the requested port was taken) — what the
            # dashboard and the serve concurrency tests read back.
            summary["endpoint"] = self._endpoint_info
        if self.tracer is not None:
            summary["spans"] = self.tracer.span_summary()
            # Critical-path verdicts: per-stage and whole-run dominant
            # bottleneck from the span timeline (wall-clock interval
            # unions, so concurrent lanes never double-count).
            try:
                from .obs import critpath as _critpath

                summary["critpath"] = _critpath.analyze(
                    summary, self.tracer.events)
            except Exception:
                log.warning("critical-path analysis failed", exc_info=True)
            tdir = _export.run_trace_dir(self.name)
            os.makedirs(tdir, exist_ok=True)
            summary["trace_file"] = _export.write_trace(
                self.tracer, os.path.join(tdir, _export.TRACE_FILE),
                metrics=self.metrics)
            spath = os.path.join(tdir, _export.STATS_FILE)
            summary["stats_file"] = spath
            _export.write_stats(summary, spath)
            log.info("trace: %s · stats: %s", summary["trace_file"], spath)
            # Fleet merge: rank 0 of a healthy multi-process traced run
            # waits (bounded) for its siblings' per-rank artifacts, then
            # builds the merged clock-aligned timeline + the
            # stats()["fleet"] section — persisted back into stats.json
            # AND visible on the in-memory summary.  A dead sibling
            # cannot wedge the survivor: past fleet_wait_ms the merge
            # proceeds with whatever landed and records the missing
            # ranks.  Single-process runs never enter (back-compat pin:
            # no fleet section, identical artifact layout).
            proc = summary.get("process") or {}
            if (proc.get("num_processes", 1) > 1
                    and not proc.get("process_id")
                    and not self._run_failed
                    and settings.fleet_wait_ms > 0):
                try:
                    from .obs import fleet as _fleet

                    fl = _fleet.merge_run(
                        self.name, wait_ms=settings.fleet_wait_ms,
                        summary=summary)
                    if fl is not None:
                        summary["fleet"] = fl
                except Exception:
                    log.warning("fleet trace merge failed", exc_info=True)
        self.run_summary = summary
        if not self._run_failed:
            # Run-history corpus: one compact record per FINALIZED run
            # (failed runs would poison the adaptation medians) — the
            # accumulated telemetry plan/cost.py and doctor consume.
            from .obs import history as _history

            hpath = _history.append(summary)
            proc = summary.get("process") or {}
            if (hpath and settings.sentry_window > 0
                    and not proc.get("process_id")):
                # Long-horizon telemetry: fold this run into the compact
                # per-fingerprint series (rank 0 only — sibling ranks'
                # records are rank-tagged trail, not run-level points),
                # then ask the sentry whether the newest point regressed
                # against its trailing baseline.  Warn-only here: a
                # finalized run must never fail on its own telemetry.
                try:
                    from .obs import sentry as _sentry
                    from .obs import timeseries as _timeseries

                    _timeseries.append_from_summary(summary)
                    findings = _sentry.check_run(self.name, summary=summary)
                    if findings:
                        summary["sentry"] = findings
                        if self.logstream is not None:
                            for f in findings:
                                self.logstream.emit(
                                    "warn", "sentry-regression",
                                    "{metric} regressed: {value:g} vs "
                                    "baseline median {median:g} "
                                    "(z={z:.1f}, window={window})".format(
                                        **f),
                                    data=f)
                except Exception:
                    log.warning("telemetry sentry failed", exc_info=True)

    def _run(self, outputs, cleanup=True):
        from . import resume as _resume
        # EVERY run holds the scratch root's liveness lock — named roots
        # are shared across runs whether or not they resume, and a
        # concurrent run's in-flight spill blocks are not manifest-
        # referenced until its stage completes.  The GC sweep fires only
        # when the exclusive probe proves no other live run is mid-flight
        # under this name; we then downgrade to shared for our duration.
        guard = _resume.RunGuard(self.store.root)
        try:
            # Inside the try so a failure in the sweep or the shared
            # downgrade can never leak the flock fd (which would block
            # other runs' GC under this name until process exit).
            if guard.exclusive:
                _resume.gc_unreferenced(self.store.root)
            guard.share()
            return self._run_stages(outputs, cleanup)
        except BaseException:
            # Drain-on-kill: a failing/killed run discards its queued
            # background spill writes (refs keep their RAM blocks; no
            # temp files survive) instead of racing them against teardown.
            try:
                self.store.abort_writes()
            except Exception:
                log.warning("spill writer abort failed", exc_info=True)
            # HBM residents die with the run: a killed run's device
            # lanes are never consumed, and holding them would leak the
            # shared device budget (the handoff tier keeps whole
            # vocabularies resident mid-stage).
            try:
                self.store.release_device()
            except Exception:
                log.warning("device release failed", exc_info=True)
            raise
        finally:
            guard.close()

    def _entry_io(self, entry):
        """Best-effort (records, bytes) of a stage input/output entry.
        Materialized PartitionSets and sink part files have exact sizes;
        raw taps (Chunkers) report (None, None) — their size is unknowable
        without reading them."""
        if isinstance(entry, storage.PartitionSet):
            recs = nbytes = 0
            for r in entry.all_refs():
                recs += len(r)
                nbytes += r.total_bytes
            return recs, nbytes
        if isinstance(entry, _SinkOutput):
            nbytes = 0
            for p in entry.paths:
                try:
                    nbytes += os.path.getsize(p)
                except OSError:
                    pass
            return None, nbytes
        return None, None

    def _pressure_snap(self):
        """Store/retry counters at a stage boundary; the per-stage deltas
        become that stage's StageStats pressure fields."""
        sto = self.store
        q = self._quarantine
        return (sto.spill_count, sto.spilled_bytes, sto.merge_gens,
                sto.merge_gen_bytes, self.retries_total,
                q.count if q is not None else 0)

    def _fill_stage_io(self, st, stage, env, result, snap):
        for s in getattr(stage, "inputs", ()):
            r, b = self._entry_io(env.get(s))
            if r:
                st.records_in += r
            if b:
                st.bytes_in += b
        _r, b = self._entry_io(result)
        if b:
            st.bytes_out += b
        sto = self.store
        st.spill_count = sto.spill_count - snap[0]
        st.spill_bytes = sto.spilled_bytes - snap[1]
        st.merge_gens = sto.merge_gens - snap[2]
        st.merge_gen_bytes = sto.merge_gen_bytes - snap[3]
        st.retries = self.retries_total - snap[4]
        q = self._quarantine
        st.quarantined = (q.count - snap[5]) if q is not None else 0

    def _run_stages(self, outputs, cleanup):
        rep = self.plan_report
        if rep is not None:
            # The plan decision record on the stage timeline: how many
            # construction-order stages collapsed into the schedule below.
            _trace.instant(
                "plan", "optimize", lane="stages",
                enabled=bool(rep.get("enabled")),
                stages_before=rep.get("stages_before"),
                stages_after=rep.get("stages_after"),
                rules={k: v for k, v in (rep.get("rules") or {}).items()
                       if v})
        env = {}
        to_delete = []
        fused = {}  # sid -> (pset, nrec, njobs) computed by an earlier pass
        plan, stage_fps = {}, {}
        volatile_sources = set()
        n_stages = len(self.graph.stages)
        required = None  # None = every stage (the non-resume fast path)
        from . import resume as _resume

        if self.resume:
            stage_fps = _resume.stage_fingerprints(
                self.graph, salt="p{}".format(self.n_partitions))
            plan = _resume.load_plan(self.store.root, stage_fps)
            if plan:
                log.info("resume: %d stage(s) restorable from %s",
                         len(plan), self.store.root)
        # Cross-run materialization cache (plan/reuse.py): decisions and
        # mounts happen HERE, before the need-set walk, so a corrupted
        # entry degrades to a normal recompute while its prefix is still
        # scheduled.  Best-effort by design: any failure disarms the
        # cache for this run and the run proceeds cold.
        reuse_ctl = None
        if settings.reuse_enabled():
            from .plan import reuse as _reuse

            try:
                reuse_ctl = _reuse.RunReuse(self, outputs)
                reuse_ctl.plan(outputs, satisfied=plan)
                self._reuse_summary = reuse_ctl.summary
            except Exception:
                log.warning("reuse cache disabled for this run",
                            exc_info=True)
                reuse_ctl = None
        if self.resume or (reuse_ctl is not None
                           and (reuse_ctl.mounted or reuse_ctl.incremental)):
            # Lazy need-set: a stage executes only if its output feeds a
            # stage that executes (or is itself requested / an effectful
            # sink) AND it was not restored or mounted.  Without this, a
            # rerun whose intermediates were cleaned up would recompute
            # the whole chain below its one surviving (final-output)
            # checkpoint — or below a cache hit.
            required = set()
            needed = set(outputs)
            for sid in range(n_stages - 1, -1, -1):
                stage = self.graph.stages[sid]
                if isinstance(stage, GInput):
                    continue
                if stage.output not in needed and not isinstance(
                        stage, GSink):
                    continue
                required.add(sid)
                if sid in plan:
                    continue  # restored from checkpoint: inputs not needed
                if reuse_ctl is not None and sid in reuse_ctl.mounted:
                    continue  # mounted from the shared cache
                if reuse_ctl is not None and sid in reuse_ctl.incremental:
                    continue  # delta re-run reads only its tap (GInput
                    #           sources always populate env below)
                needed.update(stage.inputs)
        for sid, stage in enumerate(self.graph.stages):
            t0 = time.time()
            t0_span = _trace.now()
            snap = self._pressure_snap()
            self.store.set_stage(sid)
            # Fault attribution context: the stage the exchange watchdog
            # and quarantine sink tag their events with (sequential
            # walk: single writer).
            _faults.set_context(run=self.name, stage=sid)
            if isinstance(stage, GInput):
                env[stage.output] = stage.tap
                continue
            if self.profiler is not None:
                # Per-operator attribution context: the stage walk is
                # sequential, so the profiler's current-stage pointer is
                # exact; provenance (the original user stages a fused
                # node absorbed) rides the node from plan fusion.
                from .plan import ir as _plan_ir

                self.profiler.begin_stage(
                    sid, _plan_ir.stage_kind(stage),
                    provenance=_plan_ir.stage_provenance(stage))
            if _metrics.enabled():
                # The progress line's live stage view + a sampled stage
                # gauge, so the time series shows stage boundaries.
                self._status.update({
                    "sid": sid + 1, "n_stages": n_stages,
                    "kind": ("map" if isinstance(stage, GMap) else
                             "reduce" if isinstance(stage, GReduce)
                             else "sink"),
                    "stage_t0": t0, "jobs_total": 0, "jobs_done": 0})
                _metrics.gauge_set("run.stage", sid)

            if required is not None and sid not in required:
                log.info("Stage %s/%s skipped: every consumer was restored "
                         "from checkpoint", sid + 1, n_stages)
                continue
            log.info("Stage %s/%s: %r", sid + 1, n_stages, stage)
            if sid in plan:
                result, nrec = _resume.restore_stage(
                    self.store.root, plan[sid])
                env[stage.output] = result
                if not isinstance(stage, GSink):
                    to_delete.append(stage.output)
                st = StageStats(sid, "resumed-" + (
                    "map" if isinstance(stage, GMap) else
                    "reduce" if isinstance(stage, GReduce) else "sink"))
                st.n_jobs = 0
                st.records_out = nrec
                st.seconds = time.time() - t0
                self._fill_stage_io(st, stage, env, result, snap)
                self.stats.append(st)
                _trace.complete("stage", "s{}:{}".format(sid, st.kind),
                                t0_span, lane="stages", records=nrec)
                log.info("Stage %s resumed: %s", sid + 1, st.as_dict())
                continue
            if reuse_ctl is not None and reuse_ctl.handles(sid):
                out = None
                try:
                    out = reuse_ctl.apply(sid, stage, env)
                except Exception:
                    # Exactness contract: a cache entry that fails mid-
                    # apply degrades to recompute, never to wrong
                    # results — fall through to normal execution (the
                    # need-set kept an incremental stage's tap input
                    # live; full mounts were validated at plan time).
                    log.warning("reuse: stage %s falls back to recompute",
                                sid + 1, exc_info=True)
                    reuse_ctl.note_fallback(sid)
                if out is not None:
                    result, nrec, rkind = out
                    env[stage.output] = result
                    if not isinstance(stage, GSink):
                        to_delete.append(stage.output)
                    # Mounted frames persist no resume manifest: their
                    # scratch hardlinks must be DELETED (not released)
                    # at cleanup, exactly like volatile stages' blocks.
                    volatile_sources.add(stage.output)
                    self.store.drain_writes()
                    st = StageStats(sid, rkind + "-" + (
                        "map" if isinstance(stage, GMap) else
                        "reduce" if isinstance(stage, GReduce) else "sink"))
                    st.n_jobs = 0
                    st.records_out = nrec
                    st.seconds = time.time() - t0
                    self._fill_stage_io(st, stage, env, result, snap)
                    self.stats.append(st)
                    _trace.complete("stage", "s{}:{}".format(sid, st.kind),
                                    t0_span, lane="stages", records=nrec)
                    log.info("Stage %s %s from reuse cache: %s", sid + 1,
                             rkind, st.as_dict())
                    continue
            if isinstance(stage, GMap):
                if (sid not in fused
                        and len(stage.inputs) == 1
                        and type(stage.mapper) is base.Map
                        and stage.mapper.mapper is base._identity
                        and stage.combiner is None
                        and "binop" not in stage.options
                        and not stage.options.get("memory")
                        and not self.resume
                        and stage.inputs[0] not in outputs
                        and isinstance(env[stage.inputs[0]],
                                       storage.PartitionSet)
                        and env[stage.inputs[0]].n_partitions
                        == self.n_partitions
                        and self._alias_provenance_ok(stage,
                                                      env[stage.inputs[0]])):
                    # Identity checkpoint over an already-materialized
                    # partition set: alias it instead of re-registering
                    # (and re-spilling) every byte through a copy stage.
                    # The alias takes over deletion duty from the input.
                    result = env[stage.inputs[0]]
                    nrec, njobs = result.total_records(), 0
                    if stage.inputs[0] in to_delete:
                        to_delete.remove(stage.inputs[0])
                    env[stage.output] = result
                    to_delete.append(stage.output)
                    st = StageStats(sid, "map-alias")
                    st.records_out = nrec
                    st.seconds = time.time() - t0
                    self._fill_stage_io(st, stage, env, result, snap)
                    self.stats.append(st)
                    _trace.complete("stage", "s{}:map-alias".format(sid),
                                    t0_span, lane="stages", records=nrec)
                    log.info("Stage %s aliased (identity checkpoint): %s",
                             sid + 1, st.as_dict())
                    continue
                if sid in self._chain_results:
                    # Consumer half of a streamed chain: its jobs already
                    # ran, overlapped with the producer's, at the
                    # producer's turn (docs/pipeline.md).  Normal stage
                    # bookkeeping below still applies.
                    result, nrec, njobs = self._chain_results.pop(sid)
                elif sid in fused:
                    result, nrec, njobs = fused.pop(sid)
                else:
                    chained = None
                    hint = self._pipeline_edges.get(sid)
                    if (hint is not None and hint["mode"] == "chain"
                            and settings.pipeline_enabled()):
                        chained = self._run_chain(
                            sid, stage, hint["dst"], env)
                    if chained is not None:
                        result, nrec, njobs = chained
                        to_delete.append(stage.output)
                        env[stage.output] = result
                        self.store.drain_writes()
                        st = StageStats(sid, "map-chained")
                        st.n_jobs = njobs
                        st.records_out = nrec
                        st.seconds = time.time() - t0
                        self._fill_stage_io(st, stage, env, result, snap)
                        self.stats.append(st)
                        _trace.complete(
                            "stage", "s{}:map-chained".format(sid),
                            t0_span, lane="stages", records=nrec,
                            jobs=njobs)
                        log.info("Stage %s chained into s%s: %s", sid + 1,
                                 hint["dst"] + 1, st.as_dict())
                        continue
                    group = [g for g in self._scan_share_group(
                        sid, stage, env)
                        if g[0] not in plan
                        and (required is None or g[0] in required)
                        and (reuse_ctl is None
                             or not reuse_ctl.handles(g[0]))]
                    if group:
                        members = [(sid, stage)] + group
                        outs = self.run_map_group(
                            [s for s, _ in members],
                            [st for _, st in members], env)
                        for (msid, _), out in zip(members[1:], outs[1:]):
                            fused[msid] = out
                        result, nrec, njobs = outs[0]
                    else:
                        result, nrec, njobs = self.run_map(sid, stage, env)
                kind = "map"
                to_delete.append(stage.output)
            elif isinstance(stage, GReduce):
                result, nrec, njobs = self.run_reduce(sid, stage, env)
                kind = "reduce"
                to_delete.append(stage.output)
            elif isinstance(stage, GSink):
                result, nrec, njobs = self.run_sink(sid, stage, env)
                kind = "sink"  # durable: never cleaned up
            else:
                raise TypeError("Unknown stage type: {!r}".format(stage))

            env[stage.output] = result
            # Stage-boundary write barrier: every spill this stage's
            # registration pressure queued publishes now, so per-stage
            # spill attribution stays causal and checkpoint persistence
            # below sees settled refs (a ref mid-write has no path yet
            # and would be pointlessly re-written).
            self.store.drain_writes()
            if self.resume:
                _resume.persist_stage(
                    self.store, sid, stage_fps[sid], result, nrec)
                if _resume.is_volatile(stage_fps[sid]):
                    volatile_sources.add(stage.output)
            if reuse_ctl is not None:
                # Cross-run publish rides the same settled-refs barrier
                # as checkpoint persistence: on-disk blocks hardlink in
                # for free, RAM blocks encode once.  Never fails the
                # run; chaos/quarantined runs are gated off inside.
                reuse_ctl.maybe_publish(sid, stage, result, nrec)
            # Ride the plan's shuffle choice on the stage's materialized
            # partitions: lazily-read sorted outputs (sort_by) decide
            # host-vs-mesh range redistribution at read time, after the
            # stage walk is gone.
            if isinstance(result, storage.PartitionSet):
                result.shuffle_target = self._shuffle_targets.get(sid)
            st = StageStats(sid, kind)
            st.target = (stage.options or {}).get("exec_target", "host")
            st.shuffle_target = self._shuffle_targets.get(sid)
            st.n_jobs = njobs
            st.records_out = nrec
            st.seconds = time.time() - t0
            self._fill_stage_io(st, stage, env, result, snap)
            self.stats.append(st)
            _trace.complete("stage", "s{}:{}".format(sid, kind), t0_span,
                            lane="stages", records=nrec, jobs=njobs)
            log.info("Stage %s done: %s", sid + 1, st.as_dict())

        # Final write barrier: OutputDataset readers and post-run tools see
        # every spill published (per-stage drains cover the loop; this
        # backstops runs whose last stage raised between drain points).
        self.store.drain_writes()

        sto = self.store
        if sto.h2d_bytes or sto.d2h_bytes or sto.hbm_offloads:
            log.info(
                "HBM tier: %d bytes up, %d bytes fetched back, %d offloads, "
                "peak residency %d bytes",
                sto.h2d_bytes, sto.d2h_bytes, sto.hbm_offloads,
                sto.hbm_peak_bytes)

        ret = []
        keep = set()
        for source in outputs:
            keep.add(source)
            entry = env[source]
            if isinstance(entry, storage.PartitionSet):
                ret.append(OutputDataset(entry, self.store))
            elif isinstance(entry, _SinkOutput):
                from .dataset import CatDataset
                ret.append(CatDataset(entry.datasets()))
            else:  # raw tap requested directly
                from .dataset import CatDataset
                ret.append(CatDataset(list(entry.chunks())))

        if cleanup:
            for source in to_delete:
                if source in keep:
                    continue
                entry = env.get(source)
                if any(env.get(k) is entry for k in keep):
                    # identity-checkpoint alias of a kept output: the
                    # PartitionSet is shared, deletion would empty both
                    continue
                if isinstance(entry, storage.PartitionSet):
                    if self.resume and source not in volatile_sources:
                        # Durable runs keep intermediate checkpoints on disk
                        # (a modified rerun resumes from the longest valid
                        # prefix) but release RAM residency now.
                        entry.release(self.store)
                    else:
                        # Volatile stages persist no manifest and can never
                        # be resumed — retaining their spilled blocks would
                        # grow the named scratch root without bound.
                        entry.delete(self.store)

        return ret
