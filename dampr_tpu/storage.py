"""Out-of-core storage: block refs, spill tiers, scratch layout, accounting.

Replaces the reference's disk-spill machinery — RSS-watermark writers
(dampr/dataset.py:119-262, memory.py) and the /tmp/<job>/stage_N scratch tree
(base.py:435-469) — with deterministic byte accounting: block sizes are known
exactly, so no /proc sampling is needed.  The tier order is RAM → disk
(HBM-resident arrays are transient inside kernels; host RAM is the working
tier, gzip'd pickle files the spill tier).

Every stage output lives behind :class:`BlockRef`; the per-run
:class:`RunStore` decides which refs stay hot.  ``pin=True`` refs (``cached()``
stages) never spill.
"""

import contextlib
import gzip
import logging
import os
import pickle
import shutil
import threading
import uuid

from . import settings

log = logging.getLogger("dampr_tpu.storage")


class BlockRef(object):
    """A handle to one materialized block: RAM-resident, compressed-in-RAM
    (pinned ``cached()`` blocks — the reference's MemGZipDataset tier,
    dampr/dataset.py:528-547), or spilled to disk."""

    __slots__ = ("_block", "_packed", "path", "nbytes", "nrecords",
                 "value_dtype", "key_dtype", "store", "pin")

    def __init__(self, block, store=None, pin=False):
        self._packed = None
        self.nrecords = len(block)
        self.value_dtype = block.values.dtype  # metadata survives spilling
        self.key_dtype = block.keys.dtype
        self.store = store
        self.pin = pin
        self.path = None
        if pin:
            # cached() semantics: compressed RAM, charged at compressed size
            # (never spilled to disk, decompressed per read).
            self._block = None
            self._packed = pack_block(block)
            self.nbytes = len(self._packed)
        else:
            self._block = block
            self.nbytes = block.nbytes()

    @classmethod
    def from_disk(cls, path, nrecords, nbytes, key_dtype, value_dtype):
        """Rebuild a disk-backed ref from checkpoint-manifest metadata
        (resume.py): no RAM residency, reads stream from ``path``."""
        import numpy as np

        ref = cls.__new__(cls)
        ref._block = None
        ref._packed = None
        ref.path = path
        ref.nrecords = nrecords
        ref.nbytes = nbytes
        ref.key_dtype = np.dtype(key_dtype)
        ref.value_dtype = np.dtype(value_dtype)
        ref.store = None
        ref.pin = False
        return ref

    def __len__(self):
        return self.nrecords

    @property
    def resident(self):
        return self._block is not None

    def get(self):
        blk = self._block
        if blk is None:
            if self._packed is not None:
                return unpack_block(self._packed)
            blk = load_block(self.path)
            # Do not re-cache: reduce jobs stream partitions one at a time and
            # re-residency would defeat the memory bound.
        return blk

    def iter_windows(self):
        """Stream the block in bounded windows without materializing it
        whole (resident blocks yield array-view slices)."""
        blk = self._block
        if blk is None:
            if self._packed is None:
                for w in iter_block_windows(self.path):
                    yield w
                return
            blk = unpack_block(self._packed)
        from .blocks import Block

        n = len(blk)
        for at in range(0, n, SPILL_WINDOW):
            end = min(at + SPILL_WINDOW, n)
            yield Block(
                blk.keys[at:end], blk.values[at:end],
                None if blk.h1 is None else blk.h1[at:end],
                None if blk.h2 is None else blk.h2[at:end])

    def spill(self, directory):
        if self._block is None or self.pin:
            return 0
        if self.path is None:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(directory, uuid.uuid4().hex + ".blk")
            save_block(self._block, self.path)
        # else: already durable on disk (checkpoint/resume persisted it) —
        # dropping the RAM copy is the whole spill.
        freed = self.nbytes
        self._block = None
        return freed

    def delete(self):
        self._block = None
        self._packed = None
        if self.path and os.path.exists(self.path):
            os.unlink(self.path)
            self.path = None


#: Records per spill window: the unit of streamed re-reads.  Bounded so a
#: k-way merge holds k windows, never k whole blocks.
SPILL_WINDOW = 16384


def save_block(block, path):
    """Spill wire format: a sequence of pickled columnar windows, inside one
    gzip stream for object-lane blocks or as a plain stream for fully
    numeric ones.  Windowing keeps spilled blocks *streamable* — merge
    readers hold one window per run — while numeric lanes serialize as raw
    buffers (pickle protocol 5).  Numeric columns (hashes, parsed numbers,
    counts) are mostly high-entropy, so gzip buys little and costs a
    core-bound pass each way — they spill uncompressed at disk bandwidth
    (``settings.spill_compress`` = "always"/"never" overrides the
    heuristic); readers sniff the gzip magic, so both formats coexist."""
    n = len(block)
    mode = str(settings.spill_compress).lower()
    numeric = (block.keys.dtype != object and block.values.dtype != object)
    plain = mode == "never" or (mode not in ("always", "1", "true")
                                and numeric)
    opener = (lambda: open(path, "wb")) if plain else (
        lambda: gzip.open(path, "wb",
                          compresslevel=settings.compress_level))
    with opener() as f:
        for at in range(0, max(n, 1), SPILL_WINDOW):
            end = min(at + SPILL_WINDOW, n)
            pickle.dump(
                (block.keys[at:end], block.values[at:end],
                 None if block.h1 is None else block.h1[at:end],
                 None if block.h2 is None else block.h2[at:end]),
                f, protocol=pickle.HIGHEST_PROTOCOL)


def iter_block_windows(path):
    """Stream a spilled block back window by window (bounded memory).
    Sniffs the gzip magic so compressed and plain spills coexist."""
    from .blocks import Block

    with open(path, "rb") as raw:
        magic = raw.read(2)
        raw.seek(0)
        f = gzip.GzipFile(fileobj=raw) if magic == b"\x1f\x8b" else raw
        while True:
            try:
                keys, values, h1, h2 = pickle.load(f)
            except EOFError:
                return
            yield Block(keys, values, h1, h2)


def load_block(path):
    from .blocks import Block

    return Block.concat(list(iter_block_windows(path)))


def pack_block(block):
    """Compress a block into RAM bytes (the ``cached()`` tier)."""
    import io

    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb",
                       compresslevel=settings.compress_level) as f:
        pickle.dump((block.keys, block.values, block.h1, block.h2), f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def unpack_block(data):
    import io

    from .blocks import Block

    with gzip.GzipFile(fileobj=io.BytesIO(data), mode="rb") as f:
        keys, values, h1, h2 = pickle.load(f)
    return Block(keys, values, h1, h2)


class RunStore(object):
    """Per-run block registry with a byte budget (the memory-governor analog).

    Tracks every RAM-resident ref; when residency exceeds
    ``settings.max_memory_per_stage`` the oldest unpinned refs spill to the
    run's scratch directory.  Thread-safe — map jobs register refs
    concurrently.
    """

    def __init__(self, name, budget=None):
        safe = name.replace("/", "_")
        self.root = os.path.join(settings.scratch_root, safe)
        self.budget = settings.max_memory_per_stage if budget is None else budget
        self._lock = threading.Lock()
        self._resident = []          # FIFO of RAM refs
        self._resident_bytes = 0
        self._stage = "stage_0"
        self._attempts = threading.local()
        self.spill_count = 0
        self.spilled_bytes = 0

    @contextlib.contextmanager
    def attempt(self):
        """Track every ref this thread registers inside the block; on
        exception the refs are dropped, so a retried job's failed attempt
        cannot orphan blocks against the memory budget."""
        stack = getattr(self._attempts, "stack", None)
        if stack is None:
            stack = self._attempts.stack = []
        refs = []
        stack.append(refs)
        try:
            yield refs
        except BaseException:
            for ref in refs:
                self.drop_ref(ref)
            raise
        finally:
            stack.pop()

    def set_stage(self, stage_name):
        self._stage = "stage_{}".format(stage_name)

    def register(self, block, pin=False):
        ref = BlockRef(block, store=self, pin=pin)
        stack = getattr(self._attempts, "stack", None)
        if stack:
            stack[-1].append(ref)
        with self._lock:
            self._resident.append(ref)
            self._resident_bytes += ref.nbytes
            victims = self._select_victims_locked()
        # Spill I/O happens OUTSIDE the lock: victims are already removed from
        # the resident list (each ref is selected exactly once), so concurrent
        # workers keep registering while gzip+write proceeds here.
        if victims:
            directory = os.path.join(self.root, self._stage)
            freed = 0
            for v in victims:
                freed += v.spill(directory)
            with self._lock:
                self.spill_count += len(victims)
                self.spilled_bytes += freed
        return ref

    def _select_victims_locked(self):
        """Pick oldest unpinned refs until projected residency meets the
        budget; deduct their bytes immediately so other threads see the
        budget as already relieved."""
        if self._resident_bytes <= self.budget:
            return []
        victims = []
        keep = []
        for ref in self._resident:
            if (self._resident_bytes > self.budget and not ref.pin
                    and ref.resident):
                victims.append(ref)
                self._resident_bytes -= ref.nbytes
            else:
                keep.append(ref)
        self._resident = keep
        if self._resident_bytes > self.budget:
            # Everything unpinned has spilled; what remains is cached()
            # data, already gzip-compressed in RAM.  The reference would
            # keep allocating until the OS kills it; fail loudly instead.
            raise MemoryError(
                "cached() blocks exceed the memory budget even compressed "
                "({} > {} bytes); raise the budget or drop a cached()/"
                "memory=True stage".format(
                    self._resident_bytes, self.budget))
        return victims

    def drop_ref(self, ref):
        with self._lock:
            if ref in self._resident:
                self._resident.remove(ref)
                self._resident_bytes -= ref.nbytes
        ref.delete()

    def release_ref(self, ref):
        """Free a ref's RAM residency but KEEP its on-disk file (durable
        checkpoint): the budget no longer charges it, reads stream from
        disk.  Refs that never got a path keep their RAM block (nothing
        else holds the data)."""
        with self._lock:
            if ref in self._resident:
                self._resident.remove(ref)
                self._resident_bytes -= ref.nbytes
        if ref.path is not None:
            ref._block = None

    def cleanup(self):
        """Remove the run's scratch tree (outputs the caller wants to keep
        must have been read or re-registered elsewhere first)."""
        if os.path.isdir(self.root):
            shutil.rmtree(self.root, ignore_errors=True)


class PartitionSet(object):
    """The stage-exchange format: {partition_id: [BlockRef]} — the engine
    analog of the reference's {partition: [Dataset]} dicts
    (base.py:416-433, runner.py:163-172)."""

    __slots__ = ("parts", "n_partitions")

    def __init__(self, n_partitions):
        self.parts = {}
        self.n_partitions = n_partitions

    def add(self, pid, ref):
        self.parts.setdefault(pid, []).append(ref)

    def refs(self, pid):
        return self.parts.get(pid, [])

    def all_refs(self):
        for pid in sorted(self.parts):
            for ref in self.parts[pid]:
                yield ref

    def total_records(self):
        return sum(len(r) for r in self.all_refs())

    def delete(self, store=None):
        for refs in self.parts.values():
            for ref in refs:
                if store is not None:
                    store.drop_ref(ref)
                else:
                    ref.delete()
        self.parts = {}

    def release(self, store):
        """Free RAM residency, keep disk files (checkpoint retention)."""
        for refs in self.parts.values():
            for ref in refs:
                store.release_ref(ref)
