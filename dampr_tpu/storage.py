"""Out-of-core storage: block refs, spill tiers, scratch layout, accounting.

Replaces the reference's disk-spill machinery — RSS-watermark writers
(dampr/dataset.py:119-262, memory.py) and the /tmp/<job>/stage_N scratch tree
(base.py:435-469) — with deterministic byte accounting: block sizes are known
exactly, so no /proc sampling is needed.  The tier order is HBM → RAM → disk:
numeric value lanes of reduce-feeding stage outputs stay device-resident
under ``settings.hbm_budget`` (the reduce's collective fold consumes them
in place — no host round-trip at the map→reduce boundary); device→host
offload is the first spill step, gzip'd pickle files on disk the second.

Every stage output lives behind :class:`BlockRef`; the per-run
:class:`RunStore` decides which refs stay hot.  ``pin=True`` refs (``cached()``
stages) never spill.
"""

import contextlib
import gzip
import logging
import os
import pickle
import shutil
import threading
import uuid

import numpy as np

from . import settings
from .obs import trace as _trace

log = logging.getLogger("dampr_tpu.storage")

_I32_MAX = 2 ** 31 - 1


class BlockRef(object):
    """A handle to one materialized block: HBM-resident (numeric value lane
    on device — the tier the reference never had), RAM-resident,
    compressed-in-RAM (pinned ``cached()`` blocks — the reference's
    MemGZipDataset tier, dampr/dataset.py:528-547), or spilled to disk.

    Device residency model: the VALUE lane and both hash lanes live on
    device (what the reduce-side collective fold consumes); keys and the
    hash lanes ALSO stay host-side as ``_kmeta`` (partition routing and the
    exact-key table are host metadata by design), so a device-fold reduce
    touches the value lane without any host copy in either direction.
    ``lane_abs``/``lane_min`` carry the registration-time exactness
    metadata the cross-window overflow accounting needs (computed where the
    host array still existed — no device fetch ever required for it)."""

    __slots__ = ("_block", "_packed", "path", "nbytes", "nrecords",
                 "value_dtype", "key_dtype", "store", "pin",
                 "_dev", "_kmeta", "dev_bytes", "lane_abs", "lane_min")

    def __init__(self, block, store=None, pin=False, device_prep=None):
        self._packed = None
        self.nrecords = len(block)
        self.value_dtype = block.values.dtype  # metadata survives spilling
        self.key_dtype = block.keys.dtype
        self.store = store
        self.pin = pin
        self.path = None
        self._dev = None
        self._kmeta = None
        self.dev_bytes = 0
        self.lane_abs = None
        self.lane_min = None
        if pin:
            # cached() semantics: compressed RAM, charged at compressed size
            # (never spilled to disk, decompressed per read).
            self._block = None
            self._packed = pack_block(block)
            self.nbytes = len(self._packed)
        elif device_prep is not None:
            self._put_device(block, device_prep)
        else:
            self._block = block
            self.nbytes = block.nbytes()

    # -- HBM tier ----------------------------------------------------------
    @staticmethod
    def lane_prep(values, kind_hint="sum"):
        """One pass over a value lane deciding device eligibility AND
        producing everything _put_device needs: returns None (ineligible —
        mirrors parallel.shuffle._lane_safe_values' whitelist, so a
        device-tiered block can never hit the fold's refusal path at reduce
        time) or ``(lane_vals, lane_abs, lane_min)``."""
        import jax

        x64 = jax.config.jax_enable_x64
        dt = values.dtype
        if values.ndim != 1:
            return None  # composite lanes: mesh fold lanes are 1D-shaped
        if dt == object or dt == np.uint64 or (
                dt == np.float64 and not x64):
            return None
        if dt.kind == "f":
            if dt == np.float16:
                return values.astype(np.float32), None, None
            return values, None, None
        if dt == np.bool_ or dt.kind in "iu":
            v64 = values.astype(np.int64)
            if not len(v64):
                return (v64 if x64 else v64.astype(np.int32)), 0, 0
            lo, hi = int(v64.min()), int(v64.max())
            if x64:
                # Unbounded int64 lane: a float64 abs-sum over-estimate
                # (margin applied at use) — np.abs on raw int64 could wrap
                # at int64 min.
                lane_abs = float(np.abs(v64.astype(np.float64)).sum())
                return v64, lane_abs, lo
            if lo < -_I32_MAX - 1 or hi > _I32_MAX:
                return None
            lane_abs = int(np.abs(v64).sum())
            if kind_hint == "sum" and lane_abs > _I32_MAX:
                return None
            return v64.astype(np.int32), lane_abs, lo
        return None

    def _put_device(self, block, prep):
        """Move the value lane (cast to its exact device lane dtype by
        lane_prep) and hash lanes to device; keys + hashes stay host as
        routing metadata."""
        import jax

        from .ops import devtime

        h1, h2 = block.hashes()
        lane_vals, self.lane_abs, self.lane_min = prep
        with devtime.track("transfer"), _trace.span(
                "hbm", "h2d", bytes=int(lane_vals.nbytes + h1.nbytes
                                        + h2.nbytes)):
            self._dev = (jax.device_put(lane_vals), jax.device_put(h1),
                         jax.device_put(h2))
        self.dev_bytes = lane_vals.nbytes + h1.nbytes + h2.nbytes
        self._kmeta = (block.keys, h1, h2)
        self._block = None
        # Host budget is charged for what stays host-resident; object key
        # lanes charge the same 64 B/record heuristic Block.nbytes uses
        # (raw .nbytes would count 8-byte pointers, not the strings).
        kb = (block.keys.nbytes if block.keys.dtype != object
              else len(block.keys) * 64)
        self.nbytes = kb + h1.nbytes + h2.nbytes

    @property
    def is_device(self):
        return self._dev is not None

    def device_lanes(self):
        """(values, h1, h2) jax arrays — the reduce-side fold's input."""
        return self._dev

    def host_meta(self):
        """(keys, h1, h2) host arrays (routing / exact-key table)."""
        return self._kmeta

    def offload(self):
        """Device -> host: the HBM tier's first spill step.  Returns
        (freed_dev_bytes, host_bytes_delta); the caller re-enters this ref
        into host accounting."""
        if self._dev is None:  # raced with a concurrent drop
            return 0, 0
        blk = self.get()  # one counted device fetch of the value lane
        freed = self.dev_bytes
        old_host = self.nbytes
        # Publish order matters: reduce jobs read this ref concurrently
        # (eviction runs outside the store lock), so the host block must be
        # visible BEFORE the device lanes disappear — mirroring spill(),
        # which writes ``path`` before clearing ``_block``.  A reader that
        # still sees ``_dev`` uses its own snapshot (get() below).
        self._block = blk
        self.nbytes = blk.nbytes()
        self._dev = None
        self._kmeta = None
        self.dev_bytes = 0
        return freed, self.nbytes - old_host

    @classmethod
    def from_disk(cls, path, nrecords, nbytes, key_dtype, value_dtype):
        """Rebuild a disk-backed ref from checkpoint-manifest metadata
        (resume.py): no RAM residency, reads stream from ``path``."""
        import numpy as np

        ref = cls.__new__(cls)
        ref._block = None
        ref._packed = None
        ref.path = path
        ref.nrecords = nrecords
        ref.nbytes = nbytes
        ref.key_dtype = np.dtype(key_dtype)
        ref.value_dtype = np.dtype(value_dtype)
        ref.store = None
        ref.pin = False
        ref._dev = None
        ref._kmeta = None
        ref.dev_bytes = 0
        ref.lane_abs = None
        ref.lane_min = None
        return ref

    def __len__(self):
        return self.nrecords

    @property
    def total_bytes(self):
        """Host + device bytes: what size-based gates must sum (nbytes
        alone hides an HBM-resident value lane)."""
        return self.nbytes + self.dev_bytes

    @property
    def resident(self):
        return self._block is not None

    def get(self):
        blk = self._block
        if blk is None:
            # Snapshot the device lanes + host metadata into locals: a
            # concurrent offload() publishes _block first, then clears
            # _dev/_kmeta, so a reader passing the _dev check must not
            # re-read those slots (it could otherwise unpack a None).
            dev, kmeta = self._dev, self._kmeta
            if dev is not None and kmeta is not None:
                # Host materialization of a device-resident block: one
                # value-lane fetch (counted — the HBM tier's whole point is
                # that device-fold reduces never take this path).
                from .ops import devtime

                with devtime.track("transfer"):
                    vals = np.asarray(dev[0]).astype(
                        self.value_dtype, copy=False)
                if self.store is not None:
                    self.store.count_d2h(vals.nbytes)
                keys, h1, h2 = kmeta
                from .blocks import Block

                return Block(keys, vals, h1, h2)
            blk = self._block  # re-check: offload may have just published
            if blk is not None:
                return blk
            if self._packed is not None:
                return unpack_block(self._packed)
            blk = load_block(self.path)
            # Do not re-cache: reduce jobs stream partitions one at a time and
            # re-residency would defeat the memory bound.
        return blk

    def iter_windows(self):
        """Stream the block in bounded windows without materializing it
        whole (resident blocks yield array-view slices)."""
        blk = self._block
        if blk is None:
            if self._packed is not None:
                blk = unpack_block(self._packed)
            elif self._dev is not None or self.path is None:
                # Device-resident — or an offload racing us (path exists
                # only once spilled): get() resolves the live tier with a
                # consistent snapshot.
                blk = self.get()
            else:
                for w in iter_block_windows(self.path):
                    yield w
                return
        from .blocks import Block

        n = len(blk)
        for at in range(0, n, SPILL_WINDOW):
            end = min(at + SPILL_WINDOW, n)
            yield Block(
                blk.keys[at:end], blk.values[at:end],
                None if blk.h1 is None else blk.h1[at:end],
                None if blk.h2 is None else blk.h2[at:end])

    def spill(self, directory):
        if self._block is None or self.pin:
            return 0
        if self.path is None:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(directory, uuid.uuid4().hex + ".blk")
            save_block(self._block, self.path)
        # else: already durable on disk (checkpoint/resume persisted it) —
        # dropping the RAM copy is the whole spill.
        freed = self.nbytes
        self._block = None
        return freed

    def delete(self):
        self._block = None
        self._packed = None
        self._dev = None
        self._kmeta = None
        self.dev_bytes = 0
        if self.path and os.path.exists(self.path):
            os.unlink(self.path)
            self.path = None


#: Records per spill window: the unit of streamed re-reads.  Bounded so a
#: k-way merge holds k windows, never k whole blocks.
SPILL_WINDOW = 16384


def _spill_plain(key_dtype, value_dtype):
    """Compression policy, shared by every spill writer: numeric columns
    (hashes, parsed numbers, counts) are mostly high-entropy, so gzip buys
    little and costs a core-bound pass each way — they spill uncompressed
    at disk bandwidth; object lanes compress.  ``settings.spill_compress``
    = "always"/"never" overrides the heuristic."""
    mode = str(settings.spill_compress).lower()
    numeric = key_dtype != object and value_dtype != object
    return mode == "never" or (mode not in ("always", "1", "true")
                               and numeric)


def _dump_windows(block, f, at_least_one=False):
    """Write one block onto an open spill stream as pickled columnar
    SPILL_WINDOW slices — THE wire format ``iter_block_windows`` reads."""
    n = len(block)
    for at in range(0, max(n, 1) if at_least_one else n, SPILL_WINDOW):
        end = min(at + SPILL_WINDOW, n)
        pickle.dump(
            (block.keys[at:end], block.values[at:end],
             None if block.h1 is None else block.h1[at:end],
             None if block.h2 is None else block.h2[at:end]),
            f, protocol=pickle.HIGHEST_PROTOCOL)


def save_block(block, path):
    """Spill wire format: a sequence of pickled columnar windows, inside one
    gzip stream for object-lane blocks or as a plain stream for fully
    numeric ones (``_spill_plain``; readers sniff the gzip magic, so both
    formats coexist).  Windowing keeps spilled blocks *streamable* — merge
    readers hold one window per run — while numeric lanes serialize as raw
    buffers (pickle protocol 5)."""
    plain = _spill_plain(block.keys.dtype, block.values.dtype)
    opener = (lambda: open(path, "wb")) if plain else (
        lambda: gzip.open(path, "wb",
                          compresslevel=settings.compress_level))
    with opener() as f:
        _dump_windows(block, f, at_least_one=True)


def iter_block_windows(path):
    """Stream a spilled block back window by window (bounded memory).
    Sniffs the gzip magic so compressed and plain spills coexist."""
    from .blocks import Block

    with open(path, "rb") as raw:
        magic = raw.read(2)
        raw.seek(0)
        f = gzip.GzipFile(fileobj=raw) if magic == b"\x1f\x8b" else raw
        while True:
            try:
                keys, values, h1, h2 = pickle.load(f)
            except EOFError:
                return
            yield Block(keys, values, h1, h2)


def load_block(path):
    from .blocks import Block

    return Block.concat(list(iter_block_windows(path)))


def pack_block(block):
    """Compress a block into RAM bytes (the ``cached()`` tier)."""
    import io

    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb",
                       compresslevel=settings.compress_level) as f:
        pickle.dump((block.keys, block.values, block.h1, block.h2), f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def unpack_block(data):
    import io

    from .blocks import Block

    with gzip.GzipFile(fileobj=io.BytesIO(data), mode="rb") as f:
        keys, values, h1, h2 = pickle.load(f)
    return Block(keys, values, h1, h2)


class RunStore(object):
    """Per-run block registry with a byte budget (the memory-governor analog).

    Tracks every RAM-resident ref; when residency exceeds
    ``settings.max_memory_per_stage`` the oldest unpinned refs spill to the
    run's scratch directory.  Thread-safe — map jobs register refs
    concurrently.
    """

    def __init__(self, name, budget=None):
        safe = name.replace("/", "_")
        self.root = os.path.join(settings.scratch_root, safe)
        self.budget = settings.max_memory_per_stage if budget is None else budget
        self._lock = threading.Lock()
        self._resident = []          # FIFO of RAM refs
        self._resident_bytes = 0
        self._dev_resident = []      # FIFO of HBM refs
        self._dev_bytes = 0
        self._stage = "stage_0"
        self._attempts = threading.local()
        self.spill_count = 0
        self.spilled_bytes = 0
        # HBM tier stats: the boundary evidence (h2d at registration,
        # offloads + d2h fetches after — a device-fold reduce adds zero to
        # d2h_bytes for the lanes it consumed).
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.hbm_offloads = 0
        self.hbm_peak_bytes = 0
        # Overlap executor accounting: bytes of in-flight scan windows /
        # codec output the pipelined map driver holds ahead of the fold.
        # Charged against the same budget as resident blocks (reserving
        # overlap bytes pushes resident refs out to disk), so overlapping
        # never raises the stage's memory ceiling.
        self._overlap_bytes = 0
        self.overlap_peak_bytes = 0
        # Spill-lean merge generations: bytes written by streamed run
        # compactions (register_stream) — the only re-spill generation the
        # merge planner ever pays, and only past the merge_fanin cap.
        self.merge_gen_bytes = 0
        self.merge_gens = 0

    def count_d2h(self, n):
        with self._lock:
            self.d2h_bytes += n

    # -- overlap (pipelined map driver) accounting --------------------------
    @property
    def overlap_bytes(self):
        return self._overlap_bytes

    def reserve_overlap(self, n):
        """Charge ``n`` in-flight overlap bytes against the budget; resident
        refs spill to make room, so codec readahead trades RAM residency
        instead of adding to it."""
        with self._lock:
            self._overlap_bytes += n
            self.overlap_peak_bytes = max(self.overlap_peak_bytes,
                                          self._overlap_bytes)
            victims, evicted_dev = self._select_victims_locked()
        self._spill_victims(victims, evicted_dev)

    def release_overlap(self, n):
        with self._lock:
            self._overlap_bytes = max(0, self._overlap_bytes - n)

    def hbm_budget(self):
        return settings.effective_hbm_budget()

    @contextlib.contextmanager
    def attempt(self):
        """Track every ref this thread registers inside the block; on
        exception the refs are dropped, so a retried job's failed attempt
        cannot orphan blocks against the memory budget."""
        stack = getattr(self._attempts, "stack", None)
        if stack is None:
            stack = self._attempts.stack = []
        refs = []
        stack.append(refs)
        try:
            yield refs
        except BaseException:
            for ref in refs:
                self.drop_ref(ref)
            raise
        finally:
            stack.pop()

    def set_stage(self, stage_name):
        self._stage = "stage_{}".format(stage_name)

    def register(self, block, pin=False, device=False):
        prep = None
        if (device and not pin and settings.use_device
                and self.hbm_budget() > 0
                and len(block) >= settings.hbm_min_records):
            prep = BlockRef.lane_prep(block.values)
        ref = BlockRef(block, store=self, pin=pin, device_prep=prep)
        stack = getattr(self._attempts, "stack", None)
        if stack:
            stack[-1].append(ref)
        dev_victims = []
        with self._lock:
            if ref.is_device:
                self._dev_resident.append(ref)
                self._dev_bytes += ref.dev_bytes
                self.h2d_bytes += ref.dev_bytes
                self.hbm_peak_bytes = max(self.hbm_peak_bytes,
                                          self._dev_bytes)
                dev_victims = self._select_dev_victims_locked()
            # Host accounting charges what stays host-side (full block, or
            # keys+hashes for a device-tiered ref).
            self._resident.append(ref)
            self._resident_bytes += ref.nbytes
            victims, evicted_dev = self._select_victims_locked()
        # Offload / spill I/O happens OUTSIDE the lock: victims are already
        # removed from their resident list (each ref is selected exactly
        # once), so concurrent workers keep registering while the device
        # fetch / gzip+write proceeds here.
        for v in dev_victims:
            self._offload_ref(v)
        self._spill_victims(victims, evicted_dev)
        return ref

    def register_stream(self, blocks):
        """Materialize an iterator of key-sorted window blocks straight into
        a disk-backed ref: the spill-lean merge generation.  Data streams
        file -> merge -> file in SPILL_WINDOW units and is never RAM- or
        budget-resident as a whole; the returned ref reads back through the
        normal spilled-block path (iter_windows is sequential IO).

        The compression heuristic matches save_block: decided from the
        first window's dtypes (a merged run is dtype-uniform by
        construction — its sources were windows of one logical column
        pair)."""
        directory = os.path.join(self.root, self._stage)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, uuid.uuid4().hex + ".blk")
        raw = f = None
        total_records = 0
        total_bytes = 0
        key_dtype = value_dtype = np.dtype(object)
        t0 = _trace.now()
        try:
            for blk in blocks:
                if not len(blk):
                    continue
                if f is None:
                    key_dtype = blk.keys.dtype
                    value_dtype = blk.values.dtype
                    raw = open(path, "wb")
                    f = raw if _spill_plain(key_dtype, value_dtype) else \
                        gzip.GzipFile(fileobj=raw, mode="wb",
                                      compresslevel=settings.compress_level)
                _dump_windows(blk, f)
                total_records += len(blk)
                total_bytes += blk.nbytes()
        except BaseException:
            # A failed generation (disk full, merge-source read error)
            # must not leak the fd or strand a partial .blk no ref owns.
            for h in (f, raw):
                if h is not None:
                    try:
                        h.close()
                    except OSError:
                        pass
            if raw is not None:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            raise
        else:
            if f is not None:
                f.close()
                if f is not raw:
                    raw.close()
        ref = BlockRef.from_disk(path if f is not None else None,
                                 total_records, total_bytes,
                                 key_dtype, value_dtype)
        ref.store = self
        if f is None:
            # empty stream: nothing on disk, an empty resident block
            from .blocks import Block

            ref.path = None
            ref._block = Block.empty()
        stack = getattr(self._attempts, "stack", None)
        if stack:
            stack[-1].append(ref)
        with self._lock:
            self.merge_gens += 1
            self.merge_gen_bytes += total_bytes
        _trace.complete("merge", "merge-run", t0, bytes=total_bytes,
                        records=total_records)
        return ref

    def _select_dev_victims_locked(self):
        """Oldest device refs past the HBM budget offload to host (the HBM
        tier's spill step; host pressure then cascades to disk).  Selected
        refs leave BOTH resident lists here, so no later selection — host
        victims in the same register call included — can pick them twice;
        _offload_ref re-enters them as plain host refs."""
        budget = self.hbm_budget()
        if self._dev_bytes <= budget:
            return []
        victims = []
        keep = []
        for ref in self._dev_resident:
            if self._dev_bytes > budget and ref.is_device:
                victims.append(ref)
                self._dev_bytes -= ref.dev_bytes
                if ref in self._resident:
                    self._resident.remove(ref)
                    self._resident_bytes -= ref.nbytes
            else:
                keep.append(ref)
        self._dev_resident = keep
        return victims

    def _spill_victims(self, victims, evicted_dev):
        """Spill I/O for already-selected victims (outside the lock).
        ``evicted_dev`` refs were HBM-resident with unevictable host
        metadata: they offload and go straight to disk — both their device
        bytes and host bytes were already deducted."""
        if not victims and not evicted_dev:
            return
        directory = os.path.join(self.root, self._stage)
        freed = 0
        for v in evicted_dev:
            with _trace.span("hbm", "offload", bytes=v.dev_bytes):
                v.offload()
            with _trace.span("spill", "spill", bytes=v.nbytes,
                             records=v.nrecords):
                freed += v.spill(directory)
        for v in victims:
            with _trace.span("spill", "spill", bytes=v.nbytes,
                             records=v.nrecords):
                freed += v.spill(directory)
        with self._lock:
            self.spill_count += len(victims) + len(evicted_dev)
            self.spilled_bytes += freed
            self.hbm_offloads += len(evicted_dev)

    def _offload_ref(self, ref):
        """Device -> host for one ref already removed from both resident
        lists (outside the lock), then re-enter it as a plain host ref,
        which may cascade to a disk spill."""
        with _trace.span("hbm", "offload", bytes=ref.dev_bytes):
            freed, _delta = ref.offload()
        if not freed:
            return  # raced with a concurrent drop
        with self._lock:
            self.hbm_offloads += 1
            self._resident.append(ref)
            self._resident_bytes += ref.nbytes
            victims, evicted_dev = self._select_victims_locked()
        self._spill_victims(victims, evicted_dev)

    def _select_victims_locked(self):
        """Pick oldest unpinned refs until projected residency meets the
        budget; deduct their bytes immediately so other threads see the
        budget as already relieved.  Returns (spill_victims, evicted_dev):
        HBM-resident refs' host metadata (keys+hashes) is not spillable in
        place, so under host pressure those refs are evicted whole —
        offload + disk — and leave both accountings here.

        In-flight overlap bytes shrink the effective residency target: the
        pipelined map driver's windows are charged against the same budget,
        so readahead displaces resident blocks instead of stacking on
        top of them."""
        target = max(0, self.budget - self._overlap_bytes)
        if self._resident_bytes <= target:
            return [], []
        victims = []
        evicted_dev = []
        keep = []
        for ref in self._resident:
            if self._resident_bytes <= target or ref.pin:
                keep.append(ref)
            elif ref.resident:
                victims.append(ref)
                self._resident_bytes -= ref.nbytes
            elif ref.is_device:
                evicted_dev.append(ref)
                self._resident_bytes -= ref.nbytes
                if ref in self._dev_resident:
                    self._dev_resident.remove(ref)
                    self._dev_bytes -= ref.dev_bytes
            else:
                keep.append(ref)
        self._resident = keep
        if self._resident_bytes > self.budget:
            # Everything unpinned has spilled; what remains is cached()
            # data, already gzip-compressed in RAM.  The reference would
            # keep allocating until the OS kills it; fail loudly instead.
            raise MemoryError(
                "cached() blocks exceed the memory budget even compressed "
                "({} > {} bytes); raise the budget or drop a cached()/"
                "memory=True stage".format(
                    self._resident_bytes, self.budget))
        return victims, evicted_dev

    def drop_ref(self, ref):
        with self._lock:
            if ref in self._resident:
                self._resident.remove(ref)
                self._resident_bytes -= ref.nbytes
            if ref in self._dev_resident:
                self._dev_resident.remove(ref)
                self._dev_bytes -= ref.dev_bytes
        ref.delete()

    def release_ref(self, ref):
        """Free a ref's RAM residency but KEEP its on-disk file (durable
        checkpoint): the budget no longer charges it, reads stream from
        disk.  Refs that never got a path keep their RAM block (nothing
        else holds the data)."""
        with self._lock:
            if ref in self._resident:
                self._resident.remove(ref)
                self._resident_bytes -= ref.nbytes
        if ref.path is not None:
            ref._block = None

    def cleanup(self):
        """Remove the run's scratch tree (outputs the caller wants to keep
        must have been read or re-registered elsewhere first)."""
        if os.path.isdir(self.root):
            shutil.rmtree(self.root, ignore_errors=True)


class PartitionSet(object):
    """The stage-exchange format: {partition_id: [BlockRef]} — the engine
    analog of the reference's {partition: [Dataset]} dicts
    (base.py:416-433, runner.py:163-172).

    Provenance flags (how these refs were produced — what downstream fast
    paths may assume):

    - ``hash_routed``: every record lives in partition ``h1 % P`` (map
      outputs routed through ``split_by_partition``).  Reduce outputs are
      registered under the reduce *job's* pid without re-hashing whatever
      keys the reducer emitted, so they are NOT hash-routed.
    - ``hash_sorted``: every ref is a (h1, h2)-sorted run — the invariant
      the over-budget streaming merge (StreamingGroupedView) relies on.
    - ``key_sorted_runs``: every ref is a KEY-sorted run (ascending,
      numeric keys) registered without partition fan-out — the spill-lean
      merge plan for outputs no reduce ever consumes; the final read
      streams a k-way merge over the runs instead of re-sorting.

    The identity-checkpoint alias (runner) is gated on these: an alias may
    stand in for the elided copy stage only when the input already carries
    the invariants that stage would have established."""

    __slots__ = ("parts", "n_partitions", "hash_routed", "hash_sorted",
                 "key_sorted_runs")

    def __init__(self, n_partitions, hash_routed=False, hash_sorted=False,
                 key_sorted_runs=False):
        self.parts = {}
        self.n_partitions = n_partitions
        self.hash_routed = hash_routed
        self.hash_sorted = hash_sorted
        self.key_sorted_runs = key_sorted_runs

    def add(self, pid, ref):
        self.parts.setdefault(pid, []).append(ref)

    def refs(self, pid):
        return self.parts.get(pid, [])

    def all_refs(self):
        for pid in sorted(self.parts):
            for ref in self.parts[pid]:
                yield ref

    def total_records(self):
        return sum(len(r) for r in self.all_refs())

    def delete(self, store=None):
        for refs in self.parts.values():
            for ref in refs:
                if store is not None:
                    store.drop_ref(ref)
                else:
                    ref.delete()
        self.parts = {}

    def release(self, store):
        """Free RAM residency, keep disk files (checkpoint retention)."""
        for refs in self.parts.values():
            for ref in refs:
                store.release_ref(ref)
