"""Out-of-core storage: block refs, spill tiers, scratch layout, accounting.

Replaces the reference's disk-spill machinery — RSS-watermark writers
(dampr/dataset.py:119-262, memory.py) and the /tmp/<job>/stage_N scratch tree
(base.py:435-469) — with deterministic byte accounting: block sizes are known
exactly, so no /proc sampling is needed.  The tier order is HBM → RAM → disk:
numeric value lanes of reduce-feeding stage outputs stay device-resident
under ``settings.hbm_budget`` (the reduce's collective fold consumes them
in place — no host round-trip at the map→reduce boundary); device→host
offload is the first spill step, gzip'd pickle files on disk the second.

Every stage output lives behind :class:`BlockRef`; the per-run
:class:`RunStore` decides which refs stay hot.  ``pin=True`` refs (``cached()``
stages) never spill.

Spill I/O rides :mod:`dampr_tpu.io`: blocks spill as chunked-frame files
(independently compressed length-prefixed frames + an index footer —
parallel decompress, streamable partial reads) through a background
writer pool whose in-flight bytes are charged against the stage budget,
and spilled runs read back through a prefetching frame reader.  Pre-frame
spills (whole-file gzip / plain pickle streams) remain readable via magic
sniffing.
"""

import contextlib
import gzip
import logging
import os
import pickle
import shutil
import threading
import time
import uuid

import numpy as np

from . import settings
from .io import codecs as _codecs
from .io import frames as _frames
from .io.writer import SpillWriterPool
from .obs import metrics as _metrics
from .obs import trace as _trace

log = logging.getLogger("dampr_tpu.storage")

_I32_MAX = 2 ** 31 - 1

_warned_spill_modes = set()  # one warning per unrecognized policy string


def _file_size(path):
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


class BlockRef(object):
    """A handle to one materialized block: HBM-resident (numeric value lane
    on device — the tier the reference never had), RAM-resident,
    compressed-in-RAM (pinned ``cached()`` blocks — the reference's
    MemGZipDataset tier, dampr/dataset.py:528-547), or spilled to disk.

    Device residency model: the VALUE lane and both hash lanes live on
    device (what the reduce-side collective fold consumes); keys and the
    hash lanes ALSO stay host-side as ``_kmeta`` (partition routing and the
    exact-key table are host metadata by design), so a device-fold reduce
    touches the value lane without any host copy in either direction.
    ``lane_abs``/``lane_min`` carry the registration-time exactness
    metadata the cross-window overflow accounting needs (computed where the
    host array still existed — no device fetch ever required for it)."""

    __slots__ = ("_block", "_packed", "path", "nbytes", "nrecords",
                 "value_dtype", "key_dtype", "store", "pin",
                 "_dev", "_kmeta", "dev_bytes", "lane_abs", "lane_min",
                 "_dead", "_h2d_pending")

    def __init__(self, block, store=None, pin=False, device_prep=None):
        self._packed = None
        self._dead = False
        self._h2d_pending = 0
        self.nrecords = len(block)
        self.value_dtype = block.values.dtype  # metadata survives spilling
        self.key_dtype = block.keys.dtype
        self.store = store
        self.pin = pin
        self.path = None
        self._dev = None
        self._kmeta = None
        self.dev_bytes = 0
        self.lane_abs = None
        self.lane_min = None
        if pin:
            # cached() semantics: compressed RAM, charged at compressed size
            # (never spilled to disk, decompressed per read).
            self._block = None
            self._packed = pack_block(block)
            self.nbytes = len(self._packed)
        elif device_prep is not None:
            self._put_device(block, device_prep)
        else:
            self._block = block
            self.nbytes = block.nbytes()

    # -- HBM tier ----------------------------------------------------------
    @staticmethod
    def lane_prep(values, kind_hint="sum"):
        """One pass over a value lane deciding device eligibility AND
        producing everything _put_device needs: returns None (ineligible —
        mirrors parallel.shuffle._lane_safe_values' whitelist, so a
        device-tiered block can never hit the fold's refusal path at reduce
        time) or ``(lane_vals, lane_abs, lane_min)``."""
        import jax

        x64 = jax.config.jax_enable_x64
        dt = values.dtype
        if values.ndim != 1:
            return None  # composite lanes: mesh fold lanes are 1D-shaped
        if dt == object or dt == np.uint64 or (
                dt == np.float64 and not x64):
            return None
        if dt.kind == "f":
            if dt == np.float16:
                return values.astype(np.float32), None, None
            return values, None, None
        if dt == np.bool_ or dt.kind in "iu":
            v64 = values.astype(np.int64)
            if not len(v64):
                return (v64 if x64 else v64.astype(np.int32)), 0, 0
            lo, hi = int(v64.min()), int(v64.max())
            if x64:
                # Unbounded int64 lane: a float64 abs-sum over-estimate
                # (margin applied at use) — np.abs on raw int64 could wrap
                # at int64 min.
                lane_abs = float(np.abs(v64.astype(np.float64)).sum())
                return v64, lane_abs, lo
            if lo < -_I32_MAX - 1 or hi > _I32_MAX:
                return None
            lane_abs = int(np.abs(v64).sum())
            if kind_hint == "sum" and lane_abs > _I32_MAX:
                return None
            return v64.astype(np.int32), lane_abs, lo
        return None

    def _put_device(self, block, prep):
        """Move the value lane (cast to its exact device lane dtype by
        lane_prep) and hash lanes to device; keys + hashes stay host as
        routing metadata."""
        import jax

        from .ops import devtime

        h1, h2 = block.hashes()
        lane_vals, self.lane_abs, self.lane_min = prep
        with devtime.track("transfer"), _trace.span(
                "hbm", "h2d", bytes=int(lane_vals.nbytes + h1.nbytes
                                        + h2.nbytes)):
            self._dev = (jax.device_put(lane_vals), jax.device_put(h1),
                         jax.device_put(h2))
        self.dev_bytes = lane_vals.nbytes + h1.nbytes + h2.nbytes
        # Boundary accounting is per actual transfer, never per
        # registration: the store drains this pending charge exactly once
        # (a ref re-registered after a fallback adds nothing).
        self._h2d_pending = self.dev_bytes
        self._kmeta = (block.keys, h1, h2)
        self._block = None
        # Host budget is charged for what stays host-resident; object key
        # lanes charge the same 64 B/record heuristic Block.nbytes uses
        # (raw .nbytes would count 8-byte pointers, not the strings).
        kb = (block.keys.nbytes if block.keys.dtype != object
              else len(block.keys) * 64)
        self.nbytes = kb + h1.nbytes + h2.nbytes

    @property
    def is_device(self):
        return self._dev is not None

    def device_lanes(self):
        """(values, h1, h2) jax arrays — the reduce-side fold's input."""
        return self._dev

    def host_meta(self):
        """(keys, h1, h2) host arrays (routing / exact-key table)."""
        return self._kmeta

    def offload(self):
        """Device -> host: the HBM tier's first spill step.  Returns
        (freed_dev_bytes, host_bytes_delta); the caller re-enters this ref
        into host accounting."""
        if self._dev is None:  # raced with a concurrent drop
            return 0, 0
        blk = self.get()  # one counted device fetch of the value lane
        freed = self.dev_bytes
        old_host = self.nbytes
        # Publish order matters: reduce jobs read this ref concurrently
        # (eviction runs outside the store lock), so the host block must be
        # visible BEFORE the device lanes disappear — mirroring spill(),
        # which writes ``path`` before clearing ``_block``.  A reader that
        # still sees ``_dev`` uses its own snapshot (get() below).
        self._block = blk
        self.nbytes = blk.nbytes()
        self._dev = None
        self._kmeta = None
        self.dev_bytes = 0
        return freed, self.nbytes - old_host

    @classmethod
    def from_device_lanes(cls, keys, h1, h2, dev_vals, dev_h1, dev_h2,
                          store=None, value_dtype=None, lane_abs=None,
                          lane_min=None, h2d_bytes=0):
        """Build an HBM-resident ref straight from ALREADY-device-resident
        lanes (the cross-stage handoff tier): a lowered producer's program
        outputs become the consuming fold's input without ever leaving the
        device.  ``keys``/``h1``/``h2`` are the host routing metadata
        (the same ``_kmeta`` contract as ``_put_device``);
        ``value_dtype`` is the dtype ``get()`` materializes on the host
        fallback path (what the spill path would have registered);
        ``h2d_bytes`` charges only what was genuinely uploaded to
        assemble the ref (hash lanes), never the value lane — it was
        already resident."""
        ref = cls.__new__(cls)
        ref._packed = None
        ref._dead = False
        ref.path = None
        ref.nrecords = len(keys)
        ref.value_dtype = (np.dtype(value_dtype) if value_dtype is not None
                           else np.dtype(dev_vals.dtype))
        ref.key_dtype = keys.dtype
        ref.store = store
        ref.pin = False
        ref._dev = (dev_vals, dev_h1, dev_h2)
        ref._kmeta = (keys, h1, h2)
        ref._block = None
        ref.dev_bytes = int(dev_vals.nbytes + dev_h1.nbytes
                            + dev_h2.nbytes)
        ref._h2d_pending = int(h2d_bytes)
        ref.lane_abs = lane_abs
        ref.lane_min = lane_min
        kb = (keys.nbytes if keys.dtype != object else len(keys) * 64)
        ref.nbytes = kb + h1.nbytes + h2.nbytes
        return ref

    @classmethod
    def from_disk(cls, path, nrecords, nbytes, key_dtype, value_dtype):
        """Rebuild a disk-backed ref from checkpoint-manifest metadata
        (resume.py): no RAM residency, reads stream from ``path``."""
        import numpy as np

        ref = cls.__new__(cls)
        ref._block = None
        ref._packed = None
        ref.path = path
        ref.nrecords = nrecords
        ref.nbytes = nbytes
        ref.key_dtype = np.dtype(key_dtype)
        ref.value_dtype = np.dtype(value_dtype)
        ref.store = None
        ref.pin = False
        ref._dev = None
        ref._kmeta = None
        ref.dev_bytes = 0
        ref.lane_abs = None
        ref.lane_min = None
        ref._dead = False
        ref._h2d_pending = 0
        return ref

    def __len__(self):
        return self.nrecords

    @property
    def total_bytes(self):
        """Host + device bytes: what size-based gates must sum (nbytes
        alone hides an HBM-resident value lane)."""
        return self.nbytes + self.dev_bytes

    @property
    def resident(self):
        return self._block is not None

    def get(self):
        blk = self._block
        if blk is None:
            # Snapshot the device lanes + host metadata into locals: a
            # concurrent offload() publishes _block first, then clears
            # _dev/_kmeta, so a reader passing the _dev check must not
            # re-read those slots (it could otherwise unpack a None).
            dev, kmeta = self._dev, self._kmeta
            if dev is not None and kmeta is not None:
                # Host materialization of a device-resident block: one
                # value-lane fetch (counted — the HBM tier's whole point is
                # that device-fold reduces never take this path).
                from .ops import devtime

                with devtime.track("transfer"):
                    vals = np.asarray(dev[0]).astype(
                        self.value_dtype, copy=False)
                if self.store is not None:
                    self.store.count_d2h(vals.nbytes)
                keys, h1, h2 = kmeta
                from .blocks import Block

                return Block(keys, vals, h1, h2)
            blk = self._block  # re-check: offload may have just published
            if blk is not None:
                return blk
            if self._packed is not None:
                return unpack_block(self._packed)
            blk = load_block(self.path, self.store)
            # Do not re-cache: reduce jobs stream partitions one at a time and
            # re-residency would defeat the memory bound.
        return blk

    def iter_windows(self):
        """Stream the block in bounded windows without materializing it
        whole (resident blocks yield array-view slices)."""
        blk = self._block
        if blk is None:
            if self._packed is not None:
                blk = unpack_block(self._packed)
            elif self._dev is not None or self.path is None:
                # Device-resident — or an offload racing us (path exists
                # only once spilled): get() resolves the live tier with a
                # consistent snapshot.
                blk = self.get()
            else:
                for w in iter_block_windows(self.path, self.store):
                    yield w
                return
        from .blocks import Block

        n = len(blk)
        for at in range(0, n, SPILL_WINDOW):
            end = min(at + SPILL_WINDOW, n)
            yield Block(
                blk.keys[at:end], blk.values[at:end],
                None if blk.h1 is None else blk.h1[at:end],
                None if blk.h2 is None else blk.h2[at:end])

    def spill(self, directory):
        if self._block is None or self.pin:
            return 0
        if self.path is None:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, uuid.uuid4().hex + ".blk")
            t0 = time.perf_counter()

            def write_once():
                # Same transient-retry + fault-site contract as the
                # background writer pool ("wb" truncates, so a retried
                # partial write is idempotent).
                from . import faults as _faults

                _faults.check("spill_write")
                save_block(self._block, path)

            from . import faults as _faults

            _faults.retry_io(write_once, "spill_write")
            secs = time.perf_counter() - t0
            self.path = path
            # The synchronous path feeds the same io bandwidth counters
            # as the writer pool, so spill_write_mbps stays comparable
            # with DAMPR_TPU_SPILL_WRITERS=0 (the async-off baseline).
            if self.store is not None:
                self.store.count_spill_write(_file_size(path), secs)
        # else: already durable on disk (checkpoint/resume persisted it) —
        # dropping the RAM copy is the whole spill.
        freed = self.nbytes
        self._block = None
        return freed

    def delete(self):
        # Serialized against the background writer's publish (both take
        # the store lock): either the publish lands first and this delete
        # unlinks the published file, or the ``_dead`` flag lands first
        # and the publish unlinks its own write — a dropped ref can never
        # leak a freshly spilled file either way.
        store = self.store
        if store is not None:
            with store._lock:
                self._delete_inner()
        else:
            self._delete_inner()

    def _delete_inner(self):
        self._dead = True
        self._block = None
        self._packed = None
        self._dev = None
        self._kmeta = None
        self.dev_bytes = 0
        if self.path and os.path.exists(self.path):
            os.unlink(self.path)
            self.path = None


#: Records per spill window: the unit of streamed re-reads.  Bounded so a
#: k-way merge holds k windows, never k whole blocks.
SPILL_WINDOW = 16384


def _spill_codec(key_dtype, value_dtype):
    """Compression policy, shared by every spill writer: numeric columns
    (hashes, parsed numbers, counts) are mostly high-entropy, so a codec
    buys little and costs a core-bound pass each way — they spill as raw
    frames at disk bandwidth; object lanes compress with the configured
    codec (``settings.spill_codec``).  ``settings.spill_compress`` =
    "always"/"never" overrides the heuristic, and a codec name there
    ("zstd", "zlib:6", ...) means always-compress with that codec."""
    mode = str(settings.spill_compress).lower()
    if mode in ("never", "0", "false", "none", "raw"):
        return _codecs.resolve("raw")
    if mode in ("always", "1", "true"):
        return _codecs.resolve(settings.spill_codec,
                               settings.compress_level)
    if mode != "auto":
        try:
            return _codecs.resolve(mode, settings.compress_level)
        except ValueError:
            # Tolerate unrecognized policy strings the way the old
            # boolean heuristic did ("on", "yes", ... behaved as auto):
            # a config typo must not fail the run at its first spill.
            if mode not in _warned_spill_modes:
                _warned_spill_modes.add(mode)
                log.warning("unrecognized settings.spill_compress %r; "
                            "using the 'auto' policy", mode)
    if key_dtype != object and value_dtype != object:
        return _codecs.resolve("raw")
    return _codecs.resolve(settings.spill_codec,
                           settings.compress_level)


def save_block(block, path):
    """Spill wire format (dampr_tpu.io.frames): pickled columnar
    SPILL_WINDOW slices, each an independently compressed length-prefixed
    frame, with an index footer — frames decompress in parallel and merge
    readers stream partial ranges instead of inflating whole blocks.
    Readers sniff the magic, so these coexist with pre-frame gzip/plain
    spills (``iter_block_windows`` reads all three)."""
    codec = _spill_codec(block.keys.dtype, block.values.dtype)
    with open(path, "wb") as f:
        _frames.write_block_frames(block, f, codec, SPILL_WINDOW,
                                   at_least_one=True)


def _iter_legacy_windows(path, magic):
    """Pre-frame spill formats: a pickle-window stream, whole-file gzip'd
    for object-lane blocks (sniffed).  Kept verbatim so spill dirs and
    checkpoint manifests written before the frame format still load."""
    from .blocks import Block

    with open(path, "rb") as raw:
        f = gzip.GzipFile(fileobj=raw) if magic[:2] == b"\x1f\x8b" else raw
        while True:
            try:
                keys, values, h1, h2 = pickle.load(f)
            except EOFError:
                return
            yield Block(keys, values, h1, h2)


def iter_block_windows(path, store=None):
    """Stream a spilled block back window by window (bounded memory).
    Sniffs the leading magic: frame files get the prefetching frame
    reader (``settings.spill_read_prefetch`` frames in flight on the
    shared read executor); legacy gzip / plain pickle streams read
    serially.  ``store`` (when given) accrues read-bandwidth and
    ``io_wait`` accounting."""
    from .blocks import Block

    # One open serves both the magic sniff and the frame reader (the fd
    # is adopted); only the legacy formats re-open through the buffered
    # stream readers.
    fd = os.open(path, os.O_RDONLY)
    magic = os.pread(fd, 4, 0)
    if not _frames.is_frame_file(magic):
        os.close(fd)
        for w in _iter_legacy_windows(path, magic):
            yield w
        return

    on_read = on_wait = None
    if store is not None:
        on_read = store.count_spill_read

        def on_wait(secs):
            store.count_io_wait(secs, read=True)
            if _trace.enabled():
                _trace.complete("io_wait", "read-wait",
                                time.perf_counter() - secs)

    reader = _frames.FrameReader(path, fd=fd)
    payloads = reader.iter_payloads(
        settings.spill_read_prefetch, on_read, on_wait)
    try:
        for payload in payloads:
            keys, values, h1, h2 = _frames.load_window_payload(payload)
            yield Block(keys, values, h1, h2)
    finally:
        # Close the payload generator FIRST: its own finally waits out
        # in-flight prefetch reads before the fd goes away (closing the
        # fd under a live pread could hit EBADF — or a recycled fd
        # number).  The direct close is the sequential-branch backstop.
        payloads.close()
        reader.close()


def load_block(path, store=None):
    from .blocks import Block

    return Block.concat(list(iter_block_windows(path, store)))


def pack_block(block):
    """Compress a block into RAM bytes (the ``cached()`` tier)."""
    import io

    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb",
                       compresslevel=settings.compress_level) as f:
        pickle.dump((block.keys, block.values, block.h1, block.h2), f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def unpack_block(data):
    import io

    from .blocks import Block

    with gzip.GzipFile(fileobj=io.BytesIO(data), mode="rb") as f:
        keys, values, h1, h2 = pickle.load(f)
    return Block(keys, values, h1, h2)


class RunStore(object):
    """Per-run block registry with a byte budget (the memory-governor analog).

    Tracks every RAM-resident ref; when residency exceeds
    ``settings.max_memory_per_stage`` the oldest unpinned refs spill to the
    run's scratch directory.  Thread-safe — map jobs register refs
    concurrently.
    """

    def __init__(self, name, budget=None):
        safe = name.replace("/", "_")
        self.root = os.path.join(settings.scratch_root, safe)
        self.budget = settings.max_memory_per_stage if budget is None else budget
        self._lock = threading.Lock()
        self._resident = []          # FIFO of RAM refs
        self._resident_bytes = 0
        self._dev_resident = []      # FIFO of HBM refs
        self._dev_bytes = 0
        self._stage = "stage_0"
        self._attempts = threading.local()
        self.spill_count = 0
        self.spilled_bytes = 0
        # HBM tier stats: the boundary evidence (h2d at registration,
        # offloads + d2h fetches after — a device-fold reduce adds zero to
        # d2h_bytes for the lanes it consumed).
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.hbm_offloads = 0
        self.hbm_peak_bytes = 0
        # Cross-stage handoff tier (docs/plan.md "Cross-stage device
        # fusion"): device bytes registered WITHOUT a host round-trip,
        # the drain bytes the table-mode programs never fetched, and how
        # many times an edge degraded back to the spill path.
        self.handoff_active = False   # set by the runner per plan
        self.handoff_bytes = 0
        self.d2h_avoided_bytes = 0
        self.handoff_degrades = 0
        # Overlap executor accounting: bytes of in-flight scan windows /
        # codec output the pipelined map driver holds ahead of the fold.
        # Charged against the same budget as resident blocks (reserving
        # overlap bytes pushes resident refs out to disk), so overlapping
        # never raises the stage's memory ceiling.
        self._overlap_bytes = 0
        self.overlap_peak_bytes = 0
        # Spill-lean merge generations: bytes written by streamed run
        # compactions (register_stream) — the only re-spill generation the
        # merge planner ever pays, and only past the merge_fanin cap.
        self.merge_gen_bytes = 0
        self.merge_gens = 0
        # Spill I/O shape (dampr_tpu.io): post-codec bytes/seconds moved
        # by spill writes and frame reads, plus the fold-side seconds
        # spent blocked on the writer pool's backpressure or a
        # not-yet-prefetched frame — the ``io`` section of the run stats.
        self.spill_disk_bytes = 0
        self.spill_write_seconds = 0.0
        self.spill_read_bytes = 0
        self.spill_read_seconds = 0.0
        self.io_wait_seconds = 0.0        # total: write + read side
        self.io_wait_write_seconds = 0.0  # fold-side writer backpressure
        self._writer = None          # lazy SpillWriterPool

    def count_d2h(self, n):
        with self._lock:
            self.d2h_bytes += n

    def count_d2h_avoided(self, n):
        """Drain bytes a handoff-mode program batch kept device-resident
        that the classic path would have fetched (the lowered edge's
        evidence counter)."""
        with self._lock:
            self.d2h_avoided_bytes += n

    def count_handoff_degrade(self):
        with self._lock:
            self.handoff_degrades += 1

    def count_h2d(self, n):
        """Feed bytes shipped to device outside the HBM-tier register path
        (the lowered map programs' padded token matrices)."""
        with self._lock:
            self.h2d_bytes += n

    def count_spill_read(self, nbytes, secs):
        with self._lock:
            self.spill_read_bytes += nbytes
            self.spill_read_seconds += secs

    def count_spill_write(self, disk_bytes, secs):
        """One accounting point for every spill writer — sync spills,
        streamed merge generations, and the background pool all feed the
        same bandwidth counters, so their MB/s stay comparable."""
        with self._lock:
            self.spill_disk_bytes += disk_bytes
            self.spill_write_seconds += secs

    def count_io_wait(self, secs, read=False):
        """``read=False`` is the fold-side stall (a register/fold thread
        blocked on writer-pool backpressure — the number the async
        subsystem exists to keep near zero); ``read=True`` is a merge or
        final-read consumer outrunning its frame prefetch."""
        with self._lock:
            self.io_wait_seconds += secs
            if not read:
                self.io_wait_write_seconds += secs

    # -- background writer pool ---------------------------------------------
    @property
    def spill_inflight_bytes(self):
        w = self._writer
        return 0 if w is None else w.inflight_bytes

    @property
    def spill_inflight_peak_bytes(self):
        w = self._writer
        return 0 if w is None else w.inflight_peak

    @property
    def spill_queue_peak(self):
        """Deepest the writer pool's backlog ever got (queued writes) —
        the ``io.writer_queue_peak`` stats field."""
        w = self._writer
        return 0 if w is None else w.queue_peak

    def writer_pool(self):
        """The store's background spill writer, or None when disabled
        (``settings.spill_write_threads = 0`` keeps the synchronous
        pre-frame behavior)."""
        if settings.spill_write_threads <= 0:
            return None
        if self._writer is None:
            with self._lock:
                if self._writer is None:
                    cap = settings.spill_inflight_bytes
                    if not cap or cap <= 0:
                        # None/0/negative all mean "default": a 0 from
                        # the env must not become a 1-byte cap that
                        # serializes every spill.
                        cap = max(self.budget // 2, 1 << 22)
                    self._writer = SpillWriterPool(
                        self, settings.spill_write_threads, cap,
                        SPILL_WINDOW)
        return self._writer

    def publish_spill(self, ref, path, freed_ram, disk_bytes, secs,
                      clear_block=True):
        """Background-write completion: the file is durable (fsync +
        rename done), so land ``path`` and — for true spills — free the
        RAM copy.  Publish order matches the synchronous ``spill()``:
        ``path`` becomes visible before ``_block`` clears, so a reader
        passing the residency check never loses both tiers."""
        unlink = False
        with self._lock:
            if ref._dead:
                unlink = True
            else:
                ref.path = path
                if clear_block:
                    ref._block = None
                    # Counted only for live refs: a raced delete already
                    # freed this RAM itself — charging it here too would
                    # over-report spill volume (the sync path never
                    # counted deleted refs either).
                    self.spill_count += 1
                    self.spilled_bytes += freed_ram
        self.count_spill_write(disk_bytes, secs)
        if unlink:
            try:
                os.unlink(path)
            except OSError:
                pass

    def drain_writes(self):
        """Barrier: every queued spill/persist write has published.  Ran
        at stage boundaries (per-stage spill attribution stays causal) and
        before checkpoint manifests reference spill files."""
        if self._writer is not None:
            self._writer.drain()

    def abort_writes(self):
        """Kill-path drain: queued-but-unstarted writes are discarded
        (refs keep their RAM blocks); in-flight writes finish and publish.
        Budget charges released, no temp files left.  The pool flushes
        the live flight recorder first, so the crash dump's last samples
        still show the queue state at death (this runs only on failing
        runs — normal teardown goes through cleanup/close)."""
        if self._writer is not None:
            self._writer.abort(flush_recorder=True)

    # -- overlap (pipelined map driver) accounting --------------------------
    @property
    def overlap_bytes(self):
        return self._overlap_bytes

    def reserve_overlap(self, n):
        """Charge ``n`` in-flight overlap bytes against the budget; resident
        refs spill to make room, so codec readahead trades RAM residency
        instead of adding to it."""
        with self._lock:
            self._overlap_bytes += n
            self.overlap_peak_bytes = max(self.overlap_peak_bytes,
                                          self._overlap_bytes)
            victims, evicted_dev = self._select_victims_locked()
        self._spill_victims(victims, evicted_dev)

    def release_overlap(self, n):
        with self._lock:
            self._overlap_bytes = max(0, self._overlap_bytes - n)

    def hbm_budget(self):
        """HBM residency budget for this run.  When the plan produced
        device-handoff edges (``handoff_active``), the handoff budget
        applies — on forced CPU-JAX legs the plain HBM budget resolves to
        0 and would instantly offload the very refs the handoff tier just
        kept resident.  Runs without handoff edges keep the classic
        budget byte-for-byte."""
        if self.handoff_active:
            return settings.effective_handoff_budget()
        return settings.effective_hbm_budget()

    @contextlib.contextmanager
    def attempt(self):
        """Track every ref this thread registers inside the block; on
        exception the refs are dropped, so a retried job's failed attempt
        cannot orphan blocks against the memory budget.

        Attempts NEST: a successfully committed inner attempt merges its
        refs into the enclosing frame, so an outer rollback still covers
        them — the contract speculative job execution relies on (the
        retry wrapper's per-attempt frame sits inside the speculation
        layer's first-result-wins frame; a losing duplicate must roll
        back everything its retries committed)."""
        stack = getattr(self._attempts, "stack", None)
        if stack is None:
            stack = self._attempts.stack = []
        refs = []
        stack.append(refs)
        try:
            yield refs
        except BaseException:
            stack.pop()
            for ref in refs:
                self.drop_ref(ref)
            raise
        else:
            stack.pop()
            if stack:
                stack[-1].extend(refs)

    def set_stage(self, stage_name):
        self._stage = "stage_{}".format(stage_name)

    def register(self, block, pin=False, device=False, handoff=False):
        prep = None
        # hbm_min_records is a perf heuristic (tiny lanes aren't worth
        # the tier bookkeeping); a plan-decided handoff edge overrides
        # it — the edge's whole point is that the consuming fold reads
        # these lanes in place.
        floor = 1 if handoff else settings.hbm_min_records
        if (device and not pin and settings.use_device
                and self.hbm_budget() > 0
                and len(block) >= floor):
            prep = BlockRef.lane_prep(block.values)
        ref = BlockRef(block, store=self, pin=pin, device_prep=prep)
        # handoff only overrides the tier FLOOR here: these blocks came
        # through a host round trip (degrade flushes, compaction
        # merges), so they never count toward handoff_bytes — that
        # counter means "registered WITHOUT a host round-trip" and only
        # register_device() feeds it.
        return self._enter_ref(ref, handoff=False)

    def register_device(self, ref):
        """Register an already-assembled HBM-resident ref
        (:meth:`BlockRef.from_device_lanes` — the cross-stage handoff
        tier).  Same budget/attempt/metrics discipline as
        :meth:`register`; the value lane never crossed the boundary, so
        only the ref's pending hash-lane upload charges h2d."""
        ref.store = self
        return self._enter_ref(ref, handoff=True)

    def _enter_ref(self, ref, handoff=False):
        if _metrics.enabled():
            # Stage-output throughput: every materialized block crosses
            # here, so records/s and MB/s difference off these counters
            # (the progress line and the sampled series both do).
            _metrics.counter_add("store.records", ref.nrecords)
            _metrics.counter_add("store.bytes", ref.nbytes + ref.dev_bytes)
            _metrics.counter_add("store.blocks", 1)
        stack = getattr(self._attempts, "stack", None)
        if stack:
            stack[-1].append(ref)
        dev_victims = []
        with self._lock:
            if ref.is_device:
                self._dev_resident.append(ref)
                self._dev_bytes += ref.dev_bytes
                # h2d is charged per actual transfer (the ref's pending
                # counter, armed where the device_put happened), so a
                # ref re-registered after a fallback — or assembled from
                # already-resident program outputs — never double-counts
                # the boundary.
                self.h2d_bytes += ref._h2d_pending
                ref._h2d_pending = 0
                if handoff:
                    self.handoff_bytes += ref.dev_bytes
                self.hbm_peak_bytes = max(self.hbm_peak_bytes,
                                          self._dev_bytes)
                dev_victims = self._select_dev_victims_locked()
            # Host accounting charges what stays host-side (full block, or
            # keys+hashes for a device-tiered ref).
            self._resident.append(ref)
            self._resident_bytes += ref.nbytes
            victims, evicted_dev = self._select_victims_locked()
        # Offload / spill I/O happens OUTSIDE the lock: victims are already
        # removed from their resident list (each ref is selected exactly
        # once), so concurrent workers keep registering while the device
        # fetch / gzip+write proceeds here.
        for v in dev_victims:
            self._offload_ref(v)
        self._spill_victims(victims, evicted_dev)
        return ref

    def register_stream(self, blocks):
        """Materialize an iterator of key-sorted window blocks straight into
        a disk-backed ref: the spill-lean merge generation.  Data streams
        file -> merge -> file in SPILL_WINDOW units and is never RAM- or
        budget-resident as a whole; the returned ref reads back through the
        normal spilled-block path (iter_windows is sequential IO).

        The compression heuristic matches save_block: decided from the
        first window's dtypes (a merged run is dtype-uniform by
        construction — its sources were windows of one logical column
        pair)."""
        directory = os.path.join(self.root, self._stage)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, uuid.uuid4().hex + ".blk")
        raw = fw = None
        total_records = 0
        total_bytes = 0
        write_secs = 0.0
        key_dtype = value_dtype = np.dtype(object)
        t0 = _trace.now()
        try:
            for blk in blocks:
                if not len(blk):
                    continue
                if fw is None:
                    key_dtype = blk.keys.dtype
                    value_dtype = blk.values.dtype
                    raw = open(path, "wb")
                    fw = _frames.FrameWriter(
                        raw, _spill_codec(key_dtype, value_dtype))
                # Frame granularity = the spill window, regardless of the
                # (possibly multi-window) merged-round block size, so the
                # read side's one-window-per-run memory bound holds.
                w0 = time.perf_counter()
                fw.add_block(blk, SPILL_WINDOW)
                write_secs += time.perf_counter() - w0
                total_records += len(blk)
                total_bytes += blk.nbytes()
        except BaseException:
            # A failed generation (disk full, merge-source read error)
            # must not leak the fd or strand a partial .blk no ref owns.
            if raw is not None:
                try:
                    raw.close()
                except OSError:
                    pass
                try:
                    os.unlink(path)
                except OSError:
                    pass
            raise
        else:
            if fw is not None:
                # The footer/trailer write can fail too (disk full at the
                # very end): same no-leaked-fd / no-stranded-partial
                # contract as the loop body above.
                w0 = time.perf_counter()
                try:
                    fw.close()
                    raw.close()
                except BaseException:
                    try:
                        raw.close()
                    except OSError:
                        pass
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    raise
                write_secs += time.perf_counter() - w0
        ref = BlockRef.from_disk(path if fw is not None else None,
                                 total_records, total_bytes,
                                 key_dtype, value_dtype)
        ref.store = self
        if fw is None:
            # empty stream: nothing on disk, an empty resident block
            from .blocks import Block

            ref.path = None
            ref._block = Block.empty()
        stack = getattr(self._attempts, "stack", None)
        if stack:
            stack[-1].append(ref)
        if fw is not None:
            self.count_spill_write(_file_size(path), write_secs)
        if _metrics.enabled():
            _metrics.counter_add("store.records", total_records)
            _metrics.counter_add("store.bytes", total_bytes)
            _metrics.counter_add("store.blocks", 1)
        with self._lock:
            self.merge_gens += 1
            self.merge_gen_bytes += total_bytes
        _trace.complete("merge", "merge-run", t0, bytes=total_bytes,
                        records=total_records)
        return ref

    def release_device(self):
        """Drop every HBM-resident ref and return the device budget to
        zero — the failing/killed-run path.  HBM is shared across runs
        on a real accelerator, and a dead run's lanes are never
        consumed, so refs die outright (no offload copy: there is
        nothing to preserve)."""
        with self._lock:
            victims = list(self._dev_resident)
            self._dev_resident = []
            self._dev_bytes = 0
            for ref in victims:
                if ref in self._resident:
                    self._resident.remove(ref)
                    self._resident_bytes -= ref.nbytes
        for ref in victims:
            ref.delete()

    def _select_dev_victims_locked(self):
        """Oldest device refs past the HBM budget offload to host (the HBM
        tier's spill step; host pressure then cascades to disk).  Selected
        refs leave BOTH resident lists here, so no later selection — host
        victims in the same register call included — can pick them twice;
        _offload_ref re-enters them as plain host refs."""
        budget = self.hbm_budget()
        if self._dev_bytes <= budget:
            return []
        victims = []
        keep = []
        for ref in self._dev_resident:
            if self._dev_bytes > budget and ref.is_device:
                victims.append(ref)
                self._dev_bytes -= ref.dev_bytes
                if ref in self._resident:
                    self._resident.remove(ref)
                    self._resident_bytes -= ref.nbytes
            else:
                keep.append(ref)
        self._dev_resident = keep
        return victims

    def _spill_victims(self, victims, evicted_dev):
        """Spill I/O for already-selected victims (outside the lock).
        ``evicted_dev`` refs were HBM-resident with unevictable host
        metadata: they offload (synchronously — the device fetch is the
        point) and then take the same write path — both their device
        bytes and host bytes were already deducted.

        With the writer pool on, victims that need a disk write enqueue
        and the evicting thread returns immediately; their RAM stays
        readable (and charged, via the pool's in-flight bytes) until the
        background write publishes.  Victims that already own a durable
        file — checkpoint-persisted refs — just drop their RAM copy, and
        pinned/raced refs fall through to the synchronous path."""
        if not victims and not evicted_dev:
            return
        directory = os.path.join(self.root, self._stage)
        for v in evicted_dev:
            with _trace.span("hbm", "offload", bytes=v.dev_bytes):
                v.offload()
        if evicted_dev:
            with self._lock:
                self.hbm_offloads += len(evicted_dev)
        pool = self.writer_pool()
        freed_sync = n_sync = 0
        queued = []
        for v in evicted_dev + victims:
            if (pool is not None and not v.pin and v.path is None
                    and v._block is not None):
                queued.append(v)
            else:
                with _trace.span("spill", "spill", bytes=v.nbytes,
                                 records=v.nrecords):
                    freed_sync += v.spill(directory)
                n_sync += 1
        if n_sync:
            with self._lock:
                self.spill_count += n_sync
                self.spilled_bytes += freed_sync
        if queued:
            os.makedirs(directory, exist_ok=True)
            for v in queued:
                blk = v._block
                if blk is None:  # raced with a concurrent drop
                    continue
                path = os.path.join(directory, uuid.uuid4().hex + ".blk")
                pool.submit(v, blk, path,
                            _spill_codec(v.key_dtype, v.value_dtype),
                            clear_block=True)

    def _offload_ref(self, ref):
        """Device -> host for one ref already removed from both resident
        lists (outside the lock), then re-enter it as a plain host ref,
        which may cascade to a disk spill."""
        with _trace.span("hbm", "offload", bytes=ref.dev_bytes):
            freed, _delta = ref.offload()
        if not freed:
            return  # raced with a concurrent drop
        with self._lock:
            self.hbm_offloads += 1
            self._resident.append(ref)
            self._resident_bytes += ref.nbytes
            victims, evicted_dev = self._select_victims_locked()
        self._spill_victims(victims, evicted_dev)

    def _select_victims_locked(self):
        """Pick oldest unpinned refs until projected residency meets the
        budget; deduct their bytes immediately so other threads see the
        budget as already relieved.  Returns (spill_victims, evicted_dev):
        HBM-resident refs' host metadata (keys+hashes) is not spillable in
        place, so under host pressure those refs are evicted whole —
        offload + disk — and leave both accountings here.

        In-flight overlap bytes AND queued-but-unwritten spill bytes (the
        writer pool's backlog — that RAM is still held) shrink the
        effective residency target: both are charged against the same
        budget, so readahead and write queueing displace resident blocks
        instead of stacking on top of them."""
        inflight = 0 if self._writer is None else self._writer.inflight_bytes
        target = max(0, self.budget - self._overlap_bytes - inflight)
        if self._resident_bytes <= target:
            return [], []
        victims = []
        evicted_dev = []
        keep = []
        for ref in self._resident:
            if self._resident_bytes <= target or ref.pin:
                keep.append(ref)
            elif ref.resident:
                victims.append(ref)
                self._resident_bytes -= ref.nbytes
            elif ref.is_device:
                evicted_dev.append(ref)
                self._resident_bytes -= ref.nbytes
                if ref in self._dev_resident:
                    self._dev_resident.remove(ref)
                    self._dev_bytes -= ref.dev_bytes
            else:
                keep.append(ref)
        self._resident = keep
        if self._resident_bytes > self.budget:
            # Everything unpinned has spilled; what remains is cached()
            # data, already gzip-compressed in RAM.  The reference would
            # keep allocating until the OS kills it; fail loudly instead.
            raise MemoryError(
                "cached() blocks exceed the memory budget even compressed "
                "({} > {} bytes); raise the budget or drop a cached()/"
                "memory=True stage".format(
                    self._resident_bytes, self.budget))
        return victims, evicted_dev

    def drop_ref(self, ref):
        with self._lock:
            if ref in self._resident:
                self._resident.remove(ref)
                self._resident_bytes -= ref.nbytes
            if ref in self._dev_resident:
                self._dev_resident.remove(ref)
                self._dev_bytes -= ref.dev_bytes
        ref.delete()

    def release_ref(self, ref):
        """Free a ref's RAM residency but KEEP its on-disk file (durable
        checkpoint): the budget no longer charges it, reads stream from
        disk.  Refs that never got a path keep their RAM block (nothing
        else holds the data)."""
        with self._lock:
            if ref in self._resident:
                self._resident.remove(ref)
                self._resident_bytes -= ref.nbytes
        if ref.path is not None:
            ref._block = None

    def cleanup(self):
        """Remove the run's scratch tree (outputs the caller wants to keep
        must have been read or re-registered elsewhere first).  Queued
        background writes are aborted first — their target files are about
        to be deleted anyway, and their refs keep their RAM blocks."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if os.path.isdir(self.root):
            shutil.rmtree(self.root, ignore_errors=True)


class PartitionSet(object):
    """The stage-exchange format: {partition_id: [BlockRef]} — the engine
    analog of the reference's {partition: [Dataset]} dicts
    (base.py:416-433, runner.py:163-172).

    Provenance flags (how these refs were produced — what downstream fast
    paths may assume):

    - ``hash_routed``: every record lives in partition ``h1 % P`` (map
      outputs routed through ``split_by_partition``).  Reduce outputs are
      registered under the reduce *job's* pid without re-hashing whatever
      keys the reducer emitted, so they are NOT hash-routed.
    - ``hash_sorted``: every ref is a (h1, h2)-sorted run — the invariant
      the over-budget streaming merge (StreamingGroupedView) relies on.
    - ``key_sorted_runs``: every ref is a KEY-sorted run (ascending,
      numeric keys) registered without partition fan-out — the spill-lean
      merge plan for outputs no reduce ever consumes; the final read
      streams a k-way merge over the runs instead of re-sorting.

    The identity-checkpoint alias (runner) is gated on these: an alias may
    stand in for the elided copy stage only when the input already carries
    the invariants that stage would have established."""

    __slots__ = ("parts", "n_partitions", "hash_routed", "hash_sorted",
                 "key_sorted_runs", "shuffle_target", "pipeline_fold_delta")

    def __init__(self, n_partitions, hash_routed=False, hash_sorted=False,
                 key_sorted_runs=False):
        self.parts = {}
        self.n_partitions = n_partitions
        self.hash_routed = hash_routed
        self.hash_sorted = hash_sorted
        self.key_sorted_runs = key_sorted_runs
        # Host-vs-mesh routing the plan chose for the producing stage's
        # redistribution (None = undecided): lazily-read sorted outputs
        # consult it when they range-exchange at read time.
        self.shuffle_target = None
        # Streamed-edge provenance (runner pipelined executor): per-pid
        # byte shrinkage from early partial folds.  Size-gated consumers
        # add it back so their branch decisions match a staged run.
        self.pipeline_fold_delta = {}

    def add(self, pid, ref):
        self.parts.setdefault(pid, []).append(ref)

    def refs(self, pid):
        return self.parts.get(pid, [])

    def all_refs(self):
        for pid in sorted(self.parts):
            for ref in self.parts[pid]:
                yield ref

    def total_records(self):
        return sum(len(r) for r in self.all_refs())

    def delete(self, store=None):
        for refs in self.parts.values():
            for ref in refs:
                if store is not None:
                    store.drop_ref(ref)
                else:
                    ref.delete()
        self.parts = {}

    def release(self, store):
        """Free RAM residency, keep disk files (checkpoint retention)."""
        for refs in self.parts.values():
            for ref in refs:
                store.release_ref(ref)
