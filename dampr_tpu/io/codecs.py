"""Pluggable frame codecs for the chunked spill format.

Every spill frame records which codec compressed it as a one-byte id, so
files written under different ``settings`` configurations — or by
different dampr_tpu versions — coexist in one run directory and decode
correctly.  The registry is deliberately tiny:

======  ====  ==========================================================
name    id    notes
======  ====  ==========================================================
raw     0     no compression (numeric lanes are mostly high-entropy)
zlib    1     raw DEFLATE stream, level from ``settings.compress_level``
              (or ``"zlib:N"``) — no gzip header/CRC per frame
gzip    2     gzip member bytes; kept for parity with the legacy
              whole-file format (``gzip.decompress`` both ways)
lz4     3     ``lz4.frame`` — optional dependency
zstd    4     ``zstandard`` — optional dependency
======  ====  ==========================================================

The optional codecs degrade gracefully: *encoding* with an unavailable
codec falls back down the ``zstd -> lz4 -> zlib`` ladder with a one-time
warning (a config naming a codec the host lacks must not fail the run),
while *decoding* a frame whose codec module is missing raises — the
bytes cannot be conjured, and the error names the missing module.
"""

import gzip
import logging
import zlib

from ..obs import log as _obslog

log = logging.getLogger("dampr_tpu.io.codecs")

RAW, ZLIB, GZIP, LZ4, ZSTD = 0, 1, 2, 3, 4

_NAMES = {RAW: "raw", ZLIB: "zlib", GZIP: "gzip", LZ4: "lz4", ZSTD: "zstd"}
_IDS = {v: k for k, v in _NAMES.items()}
_IDS["none"] = RAW

_warned = set()


def _warn_once(key, msg, *args):
    if key not in _warned:
        _warned.add(key)
        _obslog.warn("codec-fallback", msg, *args, logger=log, codec=key)


class Codec(object):
    """One (id, name, level) encoder/decoder pair.  Instances are cheap
    value objects; ``compress``/``decompress`` operate on whole frame
    payloads (bounded by the spill window, so a few MB at most)."""

    __slots__ = ("cid", "name", "level")

    def __init__(self, cid, level=None):
        self.cid = cid
        self.name = _NAMES[cid]
        self.level = level

    def __repr__(self):
        if self.level is None:
            return "Codec[{}]".format(self.name)
        return "Codec[{}:{}]".format(self.name, self.level)

    def compress(self, data):
        if self.cid == RAW:
            return data
        if self.cid == ZLIB:
            return zlib.compress(data, self.level)
        if self.cid == GZIP:
            return gzip.compress(data, compresslevel=self.level)
        if self.cid == LZ4:
            import lz4.frame

            return lz4.frame.compress(data, compression_level=self.level)
        if self.cid == ZSTD:
            import zstandard

            return zstandard.ZstdCompressor(level=self.level).compress(data)
        raise ValueError("unknown codec id {}".format(self.cid))

    def decompress(self, data):
        return decompress(self.cid, data)


def decompress(cid, data):
    """Decode one frame payload by its recorded codec id.  Raises
    ``MissingCodecError`` when the frame needs an optional module the
    host doesn't have — the file is fine, the environment is short."""
    if cid == RAW:
        return data
    if cid == ZLIB:
        return zlib.decompress(data)
    if cid == GZIP:
        return gzip.decompress(data)
    if cid == LZ4:
        try:
            import lz4.frame
        except ImportError:
            raise MissingCodecError(
                "spill frame compressed with lz4 but the 'lz4' module is "
                "not installed (pip install lz4)")
        return lz4.frame.decompress(data)
    if cid == ZSTD:
        try:
            import zstandard
        except ImportError:
            raise MissingCodecError(
                "spill frame compressed with zstd but the 'zstandard' "
                "module is not installed (pip install zstandard)")
        return zstandard.ZstdDecompressor().decompress(data)
    raise MissingCodecError("unknown spill frame codec id {}".format(cid))


class MissingCodecError(RuntimeError):
    """A frame's codec module is unavailable on this host."""


def available(name):
    """Is ``name`` usable for encoding on this host?"""
    if name in ("raw", "none", "zlib", "gzip"):
        return True
    if name == "lz4":
        try:
            import lz4.frame  # noqa: F401
            return True
        except ImportError:
            return False
    if name == "zstd":
        try:
            import zstandard  # noqa: F401
            return True
        except ImportError:
            return False
    return False


#: Default-codec preference ladder for ``spill_codec = "auto"`` and for
#: falling back from an unavailable explicit choice: best compression/
#: speed trade first, stdlib always last.
_LADDER = ("zstd", "lz4", "zlib")

_DEFAULT_LEVELS = {
    # zlib/gzip reuse settings.compress_level (historically 1 = fast);
    # lz4/zstd levels live on their own scales.
    "lz4": 0,   # lz4.frame default (fast)
    "zstd": 3,  # zstandard default
}


def resolve(name, default_level=1):
    """Name (``"zlib"``, ``"zlib:6"``, ``"auto"``, ...) -> :class:`Codec`,
    falling back down the ladder with a one-time warning when an optional
    codec is missing."""
    spec = str(name).lower()
    name = spec
    level = None
    if ":" in name:
        name, _, lev = name.partition(":")
        try:
            level = int(lev)
        except ValueError:
            raise ValueError("bad codec level in {!r}".format(spec))
    if name != "auto" and name not in _IDS:
        raise ValueError("unknown spill codec {!r}".format(name))
    if name == "auto":
        for cand in _LADDER:
            if available(cand):
                name = cand
                break
    elif name not in ("raw", "none") and not available(name):
        for cand in _LADDER:
            if available(cand):
                _warn_once(("fallback", name),
                           "spill codec %r unavailable; falling back to %r",
                           name, cand)
                name = cand
                # The explicit level belonged to the requested codec's
                # scale (zstd goes to 22, zlib stops at 9): carrying it
                # over could fail the fallback's first compress — use the
                # fallback's own default instead.
                level = None
                break
    cid = _IDS[name]
    if level is None:
        level = _DEFAULT_LEVELS.get(name, default_level)
    return Codec(cid, level)
