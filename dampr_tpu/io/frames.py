"""Chunked-frame spill files: independently compressed, length-prefixed
frames with an index footer.

The legacy spill wire format (one pickle-window stream, optionally inside
a single gzip member) forces strictly serial decode: gzip state threads
through the whole file, so a merge reader can neither decompress frames
in parallel nor skip ahead.  This format keeps the same *payloads* — one
pickled columnar ``(keys, values, h1, h2)`` window per frame — but frames
compress independently and the footer indexes every frame, so:

- frames decompress in parallel (and out of order) on a reader pool;
- a stream reader prefetches a bounded readahead window per run during
  k-way merges without inflating whole blocks;
- byte ranges are addressable: a reader seeks straight to frame *i*.

Layout (all integers little-endian)::

    header   b"DTFR" | u8 version (1)
    frame*   u8 codec_id | u64 raw_len | u64 comp_len | payload
    footer   pickled {"frames": [(offset, codec_id, raw_len, comp_len,
                                  records), ...], "records": total}
    trailer  u64 footer_offset | b"DTFE"

Readers sniff the 4-byte header magic, so these files coexist with
legacy gzip (``\\x1f\\x8b``) and plain-pickle (``\\x80``) spills in one
run directory; the trailer magic proves the footer landed — a truncated
write (crash mid-spill) fails loudly with :class:`FrameFormatError`
instead of yielding a silently short block.
"""

import os
import pickle
import struct
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from .. import faults as _faults
from . import codecs

MAGIC = b"DTFR"
TRAILER_MAGIC = b"DTFE"
VERSION = 1

_HEADER = struct.Struct("<4sB")
_FRAME = struct.Struct("<BQQ")
_TRAILER = struct.Struct("<Q4s")


class FrameFormatError(RuntimeError):
    """Corrupt, truncated, or non-frame file where a frame file was
    expected."""


def is_frame_file(magic4):
    return magic4[:4] == MAGIC


class FrameWriter(object):
    """Append frames to an open binary file object; ``close()`` writes the
    index footer + trailer.  One writer per file, single-threaded (the
    spill pool gives each queued block its own writer)."""

    def __init__(self, f, codec):
        self.f = f
        self.codec = codec
        self.index = []
        self.records = 0
        self.raw_bytes = 0
        f.write(_HEADER.pack(MAGIC, VERSION))

    def add_frame(self, payload, records=0):
        """Compress and append one frame; returns compressed size."""
        comp = self.codec.compress(payload)
        off = self.f.tell()
        self.f.write(_FRAME.pack(self.codec.cid, len(payload), len(comp)))
        self.f.write(comp)
        self.index.append((off, self.codec.cid, len(payload), len(comp),
                           records))
        self.records += records
        self.raw_bytes += len(payload)
        return len(comp)

    def add_block(self, block, window, at_least_one=False):
        """Append one block as framed ``window``-record columnar slices —
        THE slicing every spill writer shares, so save_block files and
        streamed merge-generation files stay frame-identical."""
        n = len(block)
        for at in range(0, max(n, 1) if at_least_one else n, window):
            end = min(at + window, n)
            self.add_frame(dump_window_payload(
                block.keys[at:end], block.values[at:end],
                None if block.h1 is None else block.h1[at:end],
                None if block.h2 is None else block.h2[at:end]),
                records=end - at)

    def close(self):
        """Write footer + trailer.  The caller owns flushing/fsyncing and
        closing the underlying file (atomic-rename writers fsync before
        the rename; plain writers just close)."""
        footer_off = self.f.tell()
        self.f.write(pickle.dumps(
            {"frames": self.index, "records": self.records},
            protocol=pickle.HIGHEST_PROTOCOL))
        self.f.write(_TRAILER.pack(footer_off, TRAILER_MAGIC))


class FrameReader(object):
    """Random-access reader over one frame file.  Uses ``os.pread`` so
    concurrent prefetch tasks share a single fd without seek races."""

    def __init__(self, path, fd=None):
        """``fd``: adopt an already-open read fd for ``path`` (the caller
        sniffed the magic from it) instead of opening a second one; the
        reader owns closing it either way."""
        self.path = path
        self._fd = os.open(path, os.O_RDONLY) if fd is None else fd
        self._closed = False
        try:
            size = os.fstat(self._fd).st_size
            head = os.pread(self._fd, _HEADER.size, 0)
            if len(head) < _HEADER.size or head[:4] != MAGIC:
                raise FrameFormatError(
                    "{}: not a frame spill file".format(path))
            version = head[4]
            if version > VERSION:
                raise FrameFormatError(
                    "{}: frame format version {} is newer than this "
                    "reader (max {})".format(path, version, VERSION))
            if size < _HEADER.size + _TRAILER.size:
                raise FrameFormatError(
                    "{}: truncated frame file ({} bytes)".format(path, size))
            trailer = os.pread(self._fd, _TRAILER.size, size - _TRAILER.size)
            footer_off, tmagic = _TRAILER.unpack(trailer)
            if tmagic != TRAILER_MAGIC:
                raise FrameFormatError(
                    "{}: missing frame trailer (truncated spill — the "
                    "writer died before the footer landed)".format(path))
            flen = size - _TRAILER.size - footer_off
            if footer_off < _HEADER.size or flen <= 0:
                raise FrameFormatError(
                    "{}: frame footer offset {} out of range".format(
                        path, footer_off))
            try:
                footer = pickle.loads(
                    os.pread(self._fd, flen, footer_off))
                self.index = footer["frames"]
                self.records = footer.get("records", 0)
            except Exception as e:
                raise FrameFormatError(
                    "{}: unreadable frame footer ({})".format(path, e))
        except Exception:
            os.close(self._fd)
            self._closed = True
            raise

    def __len__(self):
        return len(self.index)

    def read_frame(self, i):
        """Read + decompress frame ``i`` -> payload bytes.  Thread-safe
        (pread); raises ``FrameFormatError`` on short reads.  Transient
        read failures (flaky disk, injected ``spill_read`` faults) retry
        in place with backoff (``settings.io_retries``) — pread of an
        immutable published file is idempotent; format errors are
        deterministic and propagate immediately."""
        return self._read_frame_timed(i)[0]

    def _read_frame_timed(self, i):
        """(payload, seconds) where seconds covers only the SUCCESSFUL
        attempt — attempt-scoped like spill attribution.  Timing the
        whole retry loop instead would fold failed attempts and their
        backoff sleeps into the store's spill_read_seconds, corrupting
        the throughput metric (mbps) every time a transient retry or a
        prefetched re-read fires."""
        cell = [0.0]

        def attempt():
            t0 = time.perf_counter()
            try:
                return self._read_frame_once(i)
            finally:
                cell[0] = time.perf_counter() - t0

        payload = _faults.retry_io(attempt, "spill_read")
        return payload, cell[0]

    def _read_frame_once(self, i):
        _faults.check("spill_read")
        off, cid, raw_len, comp_len, _records = self.index[i]
        data = os.pread(self._fd, _FRAME.size + comp_len, off)
        if len(data) < _FRAME.size + comp_len:
            raise FrameFormatError(
                "{}: frame {} truncated (indexed {} bytes at {}, file has "
                "{})".format(self.path, i, comp_len, off, len(data)))
        hcid, hraw, hcomp = _FRAME.unpack_from(data)
        if hcid != cid or hcomp != comp_len:
            raise FrameFormatError(
                "{}: frame {} header disagrees with the footer "
                "index".format(self.path, i))
        # memoryview: no second copy of the payload bytes — for raw
        # frames (the dominant numeric spill volume) the slice would
        # otherwise duplicate every byte read; pickle and the codecs all
        # accept buffers.
        payload = codecs.decompress(cid, memoryview(data)[_FRAME.size:])
        if len(payload) != raw_len:
            raise FrameFormatError(
                "{}: frame {} inflated to {} bytes, index says {}".format(
                    self.path, i, len(payload), raw_len))
        return payload

    def iter_payloads(self, prefetch=0, on_read=None, on_wait=None):
        """Yield every frame's payload in order.

        ``prefetch > 0`` keeps that many frames in flight on the shared
        read executor — reads+decompression overlap the consumer, and
        sibling streams' frames decompress in parallel.  ``on_read(nbytes,
        seconds)`` fires per frame with the compressed bytes moved and the
        read+inflate thread-seconds; ``on_wait(seconds)`` fires when the
        consumer blocked on a not-yet-done prefetch (the read-side
        ``io_wait``)."""
        n = len(self.index)
        if prefetch <= 0 or n <= 1:
            for i in range(n):
                payload, secs = self._read_frame_timed(i)
                if on_read is not None:
                    on_read(self.index[i][3], secs)
                yield payload
            return

        pool = read_executor()

        def task(i):
            payload, secs = self._read_frame_timed(i)
            return payload, self.index[i][3], secs

        pending = deque()
        nxt = 0
        try:
            while nxt < min(prefetch, n):
                pending.append(pool.submit(task, nxt))
                nxt += 1
            while pending:
                fut = pending.popleft()
                waited = 0.0
                if not fut.done():
                    w0 = time.perf_counter()
                    fut.result()
                    waited = time.perf_counter() - w0
                payload, nbytes, secs = fut.result()
                if on_read is not None:
                    on_read(nbytes, secs)
                if on_wait is not None and waited:
                    on_wait(waited)
                if nxt < n:
                    pending.append(pool.submit(task, nxt))
                    nxt += 1
                yield payload
        finally:
            # Abandoned iterator (a merge that stopped early): wait out the
            # in-flight reads before closing the fd under them, then drop
            # the results.
            for fut in pending:
                if not fut.cancel():
                    try:
                        fut.result()
                    except Exception:
                        pass
            self.close()

    def close(self):
        if not self._closed:
            self._closed = True
            os.close(self._fd)


#: Shared bounded executor for prefetch reads across every stream (a
#: k-way merge over hundreds of runs must not spawn hundreds of reader
#: threads).  Lazy: pipelines that never prefetch never start it.
_read_pool = None
_read_pool_lock = threading.Lock()


def read_executor():
    global _read_pool
    if _read_pool is None:
        with _read_pool_lock:
            if _read_pool is None:
                from .. import settings

                _read_pool = ThreadPoolExecutor(
                    max_workers=max(1, settings.spill_read_threads),
                    thread_name_prefix="dampr-io-read")
    return _read_pool


# -- block-level helpers (the spill wire payloads) ---------------------------

def dump_window_payload(keys, values, h1, h2):
    """One frame payload: the same pickled columnar window tuple the
    legacy stream format carries, so payloads are format-agnostic."""
    return pickle.dumps((keys, values, h1, h2),
                        protocol=pickle.HIGHEST_PROTOCOL)


def load_window_payload(payload):
    return pickle.loads(payload)


def write_block_frames(block, f, codec, window, at_least_one=False):
    """Write one block onto ``f`` as framed ``window``-record slices.
    Returns the FrameWriter (already closed) for its stats."""
    w = FrameWriter(f, codec)
    w.add_block(block, window, at_least_one=at_least_one)
    w.close()
    return w
