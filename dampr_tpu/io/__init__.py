"""Asynchronous spill/merge I/O: chunked-frame spill files, pluggable
codecs, a budget-charged background writer pool, and prefetching frame
readers.

The storage engine's I/O layer, factored out of :mod:`dampr_tpu.storage`
so the pieces compose: :mod:`.frames` defines the on-disk format (length-
prefixed independently compressed frames + an index footer, coexisting
with legacy gzip/pickle spills via magic sniffing), :mod:`.codecs` the
per-frame compression registry (raw/zlib/gzip always; lz4/zstd when
installed, with graceful fallback), and :mod:`.writer` the bounded
background writer pool whose in-flight bytes are charged against the
stage memory budget — see ``docs/spill_format.md`` for the format spec
and README "Spill I/O" for the knobs.
"""

from .codecs import Codec, MissingCodecError, available, resolve  # noqa: F401
from .frames import (FrameFormatError, FrameReader, FrameWriter,  # noqa: F401
                     is_frame_file)
from .writer import SpillWriterPool  # noqa: F401
