"""Background spill writer pool: codec + disk off the fold's thread.

Spill writes used to run synchronously on whichever thread tripped the
memory governor — a fold that evicted a victim paid the victim's full
compress+write before its next window.  The pool decouples them: victims
enqueue onto a small writer executor and the submitting thread returns
immediately, unless the queue is *full* — in-flight bytes are bounded and
charged against the stage memory budget (the same displacement discipline
as ``RunStore.reserve_overlap``: queued blocks' RAM is still held, so the
governor's victim target shrinks by exactly that amount).

Durability and publish order, per write::

    <final>.tmp  ->  write frames  ->  flush + fsync  ->  rename(final)
    ->  ref.path = final; ref._block = None   (under the store lock)

The ref stays fully readable through its RAM block until the rename has
landed, so concurrent readers never observe a half-written file, and
``resume.py`` manifests (written only after ``drain()``) never reference
a path that could vanish on crash.  A killed run's ``abort()`` discards
queued writes, releases their budget charges, and leaves no ``.tmp``
orphans — queued-but-unstarted jobs never touch the filesystem.

Observability: every queued write records a ``spill_queue`` span (enqueue
-> write start), the write itself a ``spill`` span on the writer thread's
lane; submitter blocking on a full queue records ``io_wait`` and feeds
the store's ``io_wait_seconds``.
"""

import logging
import os
import queue
import threading
import time

from .. import faults as _faults
from ..obs import log as _obslog
from ..obs import trace as _trace
from . import frames

log = logging.getLogger("dampr_tpu.io.writer")

_STOP = object()


class SpillWriterPool(object):
    """Bounded writer executor owned by one :class:`~dampr_tpu.storage.
    RunStore`.  Threads start lazily on first submit and are daemons (an
    abandoned store — tests, tools — never wedges interpreter exit)."""

    def __init__(self, store, threads, cap_bytes, window):
        self.store = store
        self.n_threads = max(1, threads)
        self.cap_bytes = max(1, cap_bytes)
        self.window = window
        self._q = queue.Queue()
        self._cv = threading.Condition()
        self._threads = []
        self.inflight_bytes = 0   # read by the victim selector (atomic read)
        self.inflight_peak = 0
        self._outstanding = 0
        self.queue_peak = 0       # deepest backlog ever (stats + metrics)
        self._error = None
        self._aborting = False

    # -- submit side --------------------------------------------------------
    def _ensure_threads(self):
        # Under the cv lock: concurrent first submits (two fold threads
        # tripping the governor at once) must not each spawn a worker set.
        with self._cv:
            if self._threads:
                return
            for i in range(self.n_threads):
                t = threading.Thread(
                    target=self._worker, daemon=True,
                    name="dampr-spill-writer-{}".format(i))
                t.start()
                self._threads.append(t)

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, ref, block, final_path, codec, clear_block):
        """Enqueue one block write.  ``block`` is the submitter's snapshot
        of the ref's data (the worker must not chase ``ref._block``, which
        a concurrent delete may clear).  ``clear_block=True`` is the spill
        contract (publish frees the RAM copy); ``False`` is the checkpoint-
        persist contract (the block stays hot, only ``ref.path`` lands).

        Blocks only while in-flight bytes already sit at the cap — the
        fold-side ``io_wait``.  Admission is by current backlog, not
        backlog + this block: a block larger than the cap must still be
        writable, and sizing the bound as ``cap + one block`` keeps
        sibling writer threads fed when blocks are cap-sized (the
        double-buffering this pool exists for).

        The charge is the larger of the ref's host accounting and the
        snapshot's own bytes: a device-resident ref persisted through
        the pool (checkpointing) carries metadata-only ``nbytes`` while
        its just-materialized value lane is the real queued RAM — the
        charge must bound what actually sits in the queue."""
        nbytes = max(1, ref.nbytes, block.nbytes())
        with self._cv:
            self._raise_pending()
            w0 = 0.0
            while (self.inflight_bytes >= self.cap_bytes
                   and not self._aborting):
                if not w0:
                    w0 = time.perf_counter()
                self._cv.wait(0.05)
                self._raise_pending()
            if w0:
                waited = time.perf_counter() - w0
                self.store.count_io_wait(waited)
                _trace.complete("io_wait", "writer-backpressure",
                                w0, bytes=nbytes)
            self.inflight_bytes += nbytes
            self.inflight_peak = max(self.inflight_peak, self.inflight_bytes)
            self._outstanding += 1
            self.queue_peak = max(self.queue_peak, self._outstanding)
        self._ensure_threads()
        self._q.put((ref, block, final_path, codec, clear_block, nbytes,
                     _trace.now() or time.perf_counter()))

    # -- worker side --------------------------------------------------------
    def _worker(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            ref, block, final, codec, clear_block, nbytes, t_enq = item
            if self._aborting or ref._dead:
                # Dead ref (dropped while queued — merge planners drop
                # just-merged runs routinely): skip the whole codec+
                # fsync; a publish would only unlink the file anyway.
                self._settle(nbytes)
                continue
            _trace.complete("spill_queue", "queued", t_enq, bytes=nbytes)
            tmp = final + ".tmp"

            def write_once():
                # Idempotent by construction (tmp -> fsync -> rename), so
                # transient disk failures — including injected
                # ``spill_write`` faults — retry in place with backoff
                # instead of failing the run.  The fault site sits inside
                # the retried body so chaos schedules exercise exactly
                # the production retry path.
                _faults.check("spill_write")
                try:
                    with _trace.span("spill", "spill-write", bytes=nbytes,
                                     records=len(block)):
                        with open(tmp, "wb") as f:
                            frames.write_block_frames(
                                block, f, codec, self.window,
                                at_least_one=True)
                            f.flush()
                            os.fsync(f.fileno())
                        os.replace(tmp, final)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise

            try:
                t0 = time.perf_counter()
                _faults.retry_io(write_once, "spill_write")
                secs = time.perf_counter() - t0
                try:
                    disk_bytes = os.path.getsize(final)
                except OSError:
                    disk_bytes = 0  # stats only: never fail a landed write
                self.store.publish_spill(ref, final, nbytes, disk_bytes,
                                         secs, clear_block=clear_block)
            except BaseException as e:  # disk full, codec bug: fail the run
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                with self._cv:
                    if self._error is None:
                        self._error = e
                log.error("background spill write failed: %s", e)
            finally:
                self._settle(nbytes)

    def _settle(self, nbytes):
        with self._cv:
            self.inflight_bytes = max(0, self.inflight_bytes - nbytes)
            self._outstanding -= 1
            self._cv.notify_all()

    # -- lifecycle ----------------------------------------------------------
    def drain(self):
        """Block until every queued write has published; re-raise the
        first write failure.  The stage-boundary barrier (and the step
        before any checkpoint manifest lands)."""
        with self._cv:
            while self._outstanding > 0:
                self._cv.wait(0.05)
            self._raise_pending()

    def abort(self, flush_recorder=False):
        """Kill-path drain: queued-but-unstarted writes are discarded
        (those refs keep their RAM blocks and never touched disk); a
        write a worker already started runs to completion and publishes
        normally — every ref is left in one consistent state or the
        other, budget charges are released, and no temp files remain.

        ``flush_recorder=True`` (the RunStore.abort_writes kill path —
        never normal close/cleanup) flushes the live flight recorder
        BEFORE the drain, so the crash dump's final samples capture the
        writer queue exactly as the dying run left it."""
        if flush_recorder:
            from ..obs import flightrec as _flightrec

            _flightrec.flush_active("abort_writes")
        self._aborting = True
        try:
            with self._cv:
                while self._outstanding > 0:
                    self._cv.wait(0.05)
                self._error = None
        finally:
            self._aborting = False

    def close(self):
        """Stop the worker threads (used by store cleanup; queued writes
        are aborted first).  A worker that fails to stop inside the join
        deadline is named loudly — silent thread leaks at shutdown hide
        wedged codecs/disks (the threads are daemons, so interpreter
        exit is never blocked either way)."""
        self.abort()
        for _ in self._threads:
            self._q.put(_STOP)
        for t in self._threads:
            t.join(timeout=5.0)
            if t.is_alive():
                _obslog.warn(
                    "writer-pool-stuck",
                    "spill writer thread %s did not stop within 5.0s at "
                    "shutdown; abandoning it (daemon) — a wedged codec "
                    "or disk write is still in flight", t.name,
                    logger=log, thread=t.name)
        self._threads = []
