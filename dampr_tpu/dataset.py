"""Read-side datasets: the streaming record sources stages consume.

Parity surface: reference dampr/dataset.py:420-629 (``Chunker``/``Dataset``
interfaces, ``TextLineDataset`` byte-range reading with boundary repair,
``GzipLineDataset``, ``MemoryDataset``, ``CatDataset``, ``StreamDataset``,
``EmptyDataset``).  The write side is completely different: instead of pickled
row streams we materialize columnar :class:`~dampr_tpu.blocks.Block` batches
(see storage.py for the spill tier), so the "dataset" here is mostly the *tap*
layer feeding host records into blocks, plus thin views over materialized
blocks.

Record model: every dataset yields ``(key, value)`` pairs.  Text taps yield
``(byte_offset, line)`` — the offset keys make map-only pipelines emit in input
order after the key-sorted final merge (reference semantics).
"""

import gzip
import itertools
import os

import numpy as np

from .blocks import Block


class Chunker(object):
    """Splittable input: yields independent Datasets to map over in parallel
    (reference dataset.py:420-422)."""

    def chunks(self):
        raise NotImplementedError()


class Dataset(Chunker):
    """A stream of (key, value) records (reference dataset.py:425-442)."""

    def read(self):
        raise NotImplementedError()

    def grouped_read(self):
        """Group consecutive equal keys (meaningful on key-sorted data)."""
        for key, group in itertools.groupby(self.read(), key=lambda kv: kv[0]):
            yield key, (kv[1] for kv in group)

    def delete(self):
        pass

    def __iter__(self):
        return self.read()

    def chunks(self):
        yield self


class EmptyDataset(Dataset):
    def read(self):
        return iter(())


class BlockDataset(Dataset):
    """View over a list of materialized block refs (see storage.BlockRef).

    This is the dataset form of a stage-output partition; blocks may be
    RAM-resident or spilled — ``iter_blocks`` materializes transparently.
    """

    def __init__(self, refs):
        self.refs = list(refs)

    def iter_blocks(self):
        for r in self.refs:
            yield r.get() if hasattr(r, "get") else r

    def read(self):
        for blk in self.iter_blocks():
            for kv in blk.iter_pairs():
                yield kv

    def read_lists(self, batch):
        """Batched read (runner batched-UDF path): blocks convert lane-at-
        a-time via tolist instead of record-at-a-time."""
        for blk in self.iter_blocks():
            if not len(blk):
                continue
            ks, vs = blk.to_lists()
            for i in range(0, len(ks), batch):
                yield ks[i:i + batch], vs[i:i + batch]

    def concat(self):
        return Block.concat(list(self.iter_blocks()))

    def delete(self):
        for r in self.refs:
            if hasattr(r, "delete"):
                r.delete()
        self.refs = []


class MemoryDataset(Dataset):
    """In-memory list of (k, v) pairs (reference dataset.py:590-610)."""

    def __init__(self, kvs):
        self.kvs = kvs

    def read(self):
        return iter(self.kvs)

    def read_lists(self, batch):
        kvs = self.kvs if isinstance(self.kvs, list) else list(self.kvs)
        for i in range(0, len(kvs), batch):
            part = kvs[i:i + batch]
            yield [k for k, _ in part], [v for _, v in part]


class StreamDataset(Dataset):
    """Single-shot iterator wrapper (reference dataset.py:612-620)."""

    def __init__(self, it):
        self.it = it

    def read(self):
        return self.it


class CatDataset(Dataset):
    """Concatenation of several datasets (reference dataset.py:550-565)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def read(self):
        for ds in self.datasets:
            for kv in ds.read():
                yield kv

    def chunks(self):
        for ds in self.datasets:
            yield ds

    def delete(self):
        for ds in self.datasets:
            ds.delete()


class TextLineDataset(Dataset):
    """Byte-range slice of a newline-delimited text file.

    Chunk-boundary contract (mirrors reference dataset.py:452-482, restated in
    byte terms): a chunk ``[start, end)`` with ``start > 0`` skips everything up
    to and including the first newline at-or-after ``start``; every chunk keeps
    reading through the line that crosses ``end``.  Together the two rules read
    each line exactly once across adjacent chunks, and splitting at arbitrary
    byte offsets is UTF-8 safe because ``\\n`` can never occur inside a
    multi-byte sequence (this *is* the boundary repair — no alignment probing
    needed when line-splitting happens on raw bytes).

    Keys are byte offsets of each line's first byte.
    """

    def __init__(self, path, start=0, end=None):
        self.path = path
        self.start = start
        self.end = end

    def _owned_start(self, f):
        """First byte this chunk owns: ``start`` skipped through the first
        newline at-or-after it (the one place the skip half of the boundary
        contract lives; read/read_bytes/iter_byte_blocks/read_lists share
        it).  Leaves ``f`` positioned there."""
        if self.start > 0:
            f.seek(self.start)
            f.readline()
            return f.tell()
        f.seek(0)
        return 0

    def read(self):
        with open(self.path, "rb") as f:
            pos = self._owned_start(f)
            if self.start > 0:
                if self.end is not None and pos > self.end:
                    # The skipped partial line already crossed our end: every
                    # remaining line belongs to a later chunk.  (A line longer
                    # than chunk_size would otherwise be double-read — a bug
                    # present in the reference, not replicated.)
                    return
            for raw in f:
                yield pos, raw.decode("utf-8").rstrip("\n")
                pos += len(raw)
                if self.end is not None and pos > self.end:
                    break

    def read_bytes(self):
        """The chunk's owned bytes as one buffer (for vectorized block
        mappers).  Exactly the bytes of the lines ``read()`` yields: skip
        through the first newline when start > 0, extend through the line
        that crosses ``end``."""
        with open(self.path, "rb") as f:
            real_start = self._owned_start(f)
            if self.end is None:
                return f.read()
            if real_start > self.end:
                return b""
            f.seek(self.end)
            f.readline()
            real_end = f.tell()
            f.seek(real_start)
            return f.read(real_end - real_start)

    def read_lists(self, batch):
        """Batched read for the runner's batched-UDF path: yield parallel
        ``(keys, values)`` lists of at most ``batch`` records.  Same records
        as ``read()`` — byte-offset keys, newline-stripped str values — but
        produced by C-level line splitting over bounded byte windows plus a
        vectorized offset cumsum, instead of a per-line generator."""
        carry = b""
        with open(self.path, "rb") as f:
            pos = self._owned_start(f)
        for buf in self.iter_byte_blocks():
            data = carry + buf if carry else buf
            lines = data.split(b"\n")
            carry = lines.pop()  # partial trailing line (or b"")
            if not lines:
                continue
            lens = np.fromiter(map(len, lines), dtype=np.int64,
                               count=len(lines)) + 1
            offs = pos + np.concatenate(
                ([0], np.cumsum(lens[:-1], dtype=np.int64)))
            pos += int(lens.sum())
            ks = offs.tolist()
            vs = [r.decode("utf-8") for r in lines]
            for i in range(0, len(ks), batch):
                yield ks[i:i + batch], vs[i:i + batch]
        if carry:
            yield [pos], [carry.decode("utf-8")]

    def iter_byte_blocks(self, block_size=4 * 1024 ** 2):
        """Stream the chunk's owned bytes in bounded blocks (same ownership
        contract as read_bytes) — scanning consumers (record counting)
        never materialize the whole range."""
        with open(self.path, "rb") as f:
            real_start = self._owned_start(f)
            if self.end is None:
                while True:
                    b = f.read(block_size)
                    if not b:
                        return
                    yield b
                return
            if real_start > self.end:
                return
            at = real_start
            while at < self.end:
                b = f.read(min(block_size, self.end - at))
                if not b:
                    return
                at += len(b)
                yield b
            # extend through the line crossing `end`
            tail = f.readline()
            if tail:
                yield tail

    def __repr__(self):
        return "Text[path={},start={},end={}]".format(
            self.path, self.start, self.end)


class GzipLineDataset(Dataset):
    """A .gz text file as a single unsplittable chunk (reference
    dataset.py:484-499; unsplittable per inputs.py:49-52)."""

    def __init__(self, path):
        self.path = path

    def read(self):
        with gzip.open(self.path, "rb") as f:
            pos = 0
            for raw in f:
                yield pos, raw.decode("utf-8").rstrip("\n")
                pos += len(raw)

    def read_bytes(self):
        with gzip.open(self.path, "rb") as f:
            return f.read()

    def iter_byte_blocks(self, block_size=4 * 1024 ** 2):
        """Stream decompressed bytes in bounded blocks (so consumers that
        only scan — record counting — never hold the whole expansion)."""
        with gzip.open(self.path, "rb") as f:
            while True:
                b = f.read(block_size)
                if not b:
                    return
                yield b

    def __repr__(self):
        return "GzipFile[path={}]".format(self.path)


class SinkDataset(Dataset):
    """Reads back a sink's part-file as (offset, line) — durable text written
    by a GSink stage (reference keeps sink outputs on disk, exempt from
    cleanup: runner.py:194-197)."""

    def __init__(self, path):
        self.path = path

    def read(self):
        return TextLineDataset(self.path).read()

    def delete(self):
        if os.path.exists(self.path):
            os.unlink(self.path)


class OrderKey(object):
    """Total-order wrapper for record keys: native comparison when types are
    compatible, deterministic type-name ordering otherwise.  The reference
    raises TypeError from heapq.merge on mixed-type keys (Py3); we keep mixed
    outputs readable with a stable cross-type order instead."""

    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        a, b = self.k, other.k
        try:
            return bool(a < b)
        except TypeError:
            return type(a).__name__ < type(b).__name__


def merged_read(datasets):
    """K-way merge of key-sorted datasets by key (reference MergeDataset,
    dataset.py:567-588)."""
    import heapq

    its = [ds.read() for ds in datasets]
    return heapq.merge(*its, key=lambda kv: OrderKey(kv[0]))
