"""Benchmark: the reference's headline TF-IDF workload (benchmarks/run.sh +
tf-idf-dampr.py) on dampr_tpu, vs the reference's own single-core CPU
baseline shape (benchmarks/baseline.py).

Workload (identical to reference tf-idf-dampr.py:9-21): per line, document
frequency of lowercased ``[^\\w]+``-split tokens; then idf = log(1 + total/df)
via a broadcast cross with the corpus line count; sunk as TSV.

Baseline (identical to reference benchmarks/baseline.py:12-24): single-core
Python ``Counter`` over per-line token sets, writing the same TSV.  (Both
sides drop the empty-string pseudo-token re.split emits at line edges.)

Corpus: deterministic synthetic Zipf text (the reference uses duplicated
Shakespeare; this container has no corpus and zero egress).  Size via
DAMPR_BENCH_MB (default 64).

Runs from the repo root (``python bench.py``, the driver hook) or the
installed console script ``dampr-tpu-bench``.  Prints ONE JSON line:
  {"metric": "tfidf_docfreq_throughput", "value": <MB/s>, "unit": "MB/s",
   "vs_baseline": <ours / single-core-baseline>}
"""

import json
import math
import multiprocessing
import operator
import os
import re
import shutil
import sys
import time
from collections import Counter

BENCH_DIR = os.environ.get("DAMPR_BENCH_DIR", "/tmp/dampr_tpu_bench")
BENCH_MB = int(os.environ.get("DAMPR_BENCH_MB", "128"))

RX = re.compile(r"[^\w]+")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_corpus(path, mb):
    """Deterministic Zipf-ish text: ~24k-word vocabulary, ~8-12 tokens/line
    (the Shakespeare corpus shape: 5.3MB, 23,903 unique words)."""
    import numpy as np

    if os.path.exists(path) and os.path.getsize(path) >= mb * 1024 ** 2:
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rng = np.random.RandomState(1234)
    vocab_n = 24000
    vocab = np.array(["w%04x" % i if i > 200 else "t%d" % i
                      for i in range(vocab_n)], dtype=object)
    # Zipf ranks: common words dominate like natural text
    probs = 1.0 / np.arange(1, vocab_n + 1) ** 1.1
    probs /= probs.sum()
    target = mb * 1024 ** 2
    written = 0
    with open(path, "w") as f:
        while written < target:
            ids = rng.choice(vocab_n, size=(20000,), p=probs)
            lens = rng.randint(8, 13, size=2000)
            pos = 0
            out = []
            for L in lens:
                out.append(" ".join(vocab[ids[pos:pos + L]]))
                pos += L
                if pos + 13 > len(ids):
                    break
            chunk = "\n".join(out) + "\n"
            f.write(chunk)
            written += len(chunk)
    log("corpus: {} ({:.1f} MB)".format(path, written / 1e6))


def run_baseline(corpus, outdir):
    """Reference benchmarks/baseline.py, verbatim shape: single core.

    The measured time and result are cached next to the corpus keyed on its
    (size, mtime): the baseline is deterministic and costs ~30 min at the
    10 GB tier, so re-measuring OUR side must not re-pay it.  Set
    DAMPR_BENCH_FRESH_BASELINE=1 to force a fresh baseline run."""
    import pickle

    st = os.stat(corpus)
    cache = corpus + ".baseline.pkl"
    if not os.environ.get("DAMPR_BENCH_FRESH_BASELINE"):
        try:
            with open(cache, "rb") as f:
                key, secs, counter, total = pickle.load(f)
            if key == (st.st_size, st.st_mtime_ns):
                log("baseline: cached measurement ({:.2f}s)".format(secs))
                return secs, counter, total
        except (OSError, ValueError, EOFError, pickle.UnpicklingError):
            pass
    if os.path.isdir(outdir):
        shutil.rmtree(outdir)
    os.makedirs(outdir)
    t0 = time.time()
    with open(corpus) as f:
        counter = Counter()
        num_rows = 0
        for num_rows, line in enumerate(f):
            counter.update(t for t in set(RX.split(line.lower())) if t)
        total = num_rows + 1
    with open(os.path.join(outdir, "out"), "w") as out:
        for word, count in counter.items():
            print("\t".join((word, str(count),
                             str(math.log(1 + float(total) / count)))),
                  file=out)
    secs = time.time() - t0
    try:
        with open(cache, "wb") as f:
            pickle.dump(((st.st_size, st.st_mtime_ns), secs, counter, total),
                        f, protocol=pickle.HIGHEST_PROTOCOL)
    except OSError:
        pass
    return secs, counter, total


def run_dampr_tpu(corpus, outdir):
    """Reference tf-idf-dampr.py shape on the new engine: vectorized DocFreq
    map (native tokenize+count), device-capable fold, broadcast idf join,
    TSV sink."""
    from dampr_tpu import Dampr
    from dampr_tpu.ops.text import DocFreq

    if os.path.isdir(outdir):
        shutil.rmtree(outdir)

    chunk_size = os.path.getsize(corpus) // multiprocessing.cpu_count() + 1
    t0 = time.time()
    docs = Dampr.text(corpus, chunk_size)
    # pair_values=False + fold_values: blocks keep their token keys, cached
    # hash lanes, and a numeric count column end-to-end — zero per-record
    # Python between the native tokenizer and the (device-eligible) fold
    doc_freq = (docs.custom_mapper(
        DocFreq(mode="word", lower=True, pair_values=False))
        .fold_values(operator.add))
    idf = doc_freq.cross_right(
        docs.len(),
        lambda df, total: (df[0], df[1],
                           math.log(1 + (float(total) / df[1]))),
        memory=True)
    em = idf.sink_tsv(outdir).run(name="bench-tfidf")
    secs = time.time() - t0
    return secs, em.stats()


def lint_pipelines():
    """dampr-tpu-lint discovery hook: the benchmark's pipeline shape
    (constructed over this source file; nothing runs)."""
    from dampr_tpu import Dampr
    from dampr_tpu.ops.text import DocFreq

    docs = Dampr.text(__file__, 1024 ** 2)
    doc_freq = (docs.custom_mapper(
        DocFreq(mode="word", lower=True, pair_values=False))
        .fold_values(operator.add))
    idf = doc_freq.cross_right(
        docs.len(),
        lambda df, total: (df[0], df[1],
                           math.log(1 + (float(total) / df[1]))),
        memory=True)
    return [("bench_tfidf", idf.sink_tsv("/tmp/dampr_tpu_lint_idfs"))]


def check_result(outdir, counter, total):
    got = {}
    for part in sorted(os.listdir(outdir)):
        with open(os.path.join(outdir, part)) as f:
            for line in f:
                w, c, idf = line.rstrip("\n").split("\t")
                got[w] = (int(c), float(idf))
    want = {w: (c, math.log(1 + float(total) / c))
            for w, c in counter.items()}
    assert set(got) == set(want), (
        "token sets differ: {} extra, {} missing".format(
            len(set(got) - set(want)), len(set(want) - set(got))))
    for w, (c, i) in want.items():
        gc, gi = got[w]
        assert gc == c, (w, gc, c)
        assert abs(gi - i) < 1e-9, (w, gi, i)
    return len(got)


def main():
    corpus = os.path.join(BENCH_DIR, "corpus_{}mb.txt".format(BENCH_MB))
    make_corpus(corpus, BENCH_MB)
    size_mb = os.path.getsize(corpus) / 1e6

    base_secs, counter, total = run_baseline(
        corpus, os.path.join(BENCH_DIR, "baseline-idf"))
    log("baseline (1 core): {:.2f}s = {:.1f} MB/s".format(
        base_secs, size_mb / base_secs))

    from dampr_tpu import settings as _trace_settings

    # Every bench run under one name would overwrite one trace dir, so the
    # reported artifact paths could belong to a different trial than the
    # reported (winning) numbers; give each run its own directory instead.
    old_trace_dir = _trace_settings.trace_dir
    # try/finally: a failed trial must not leave the process-global
    # trace_dir pointed at the bench scratch (main() runs in-process via
    # the bench.py driver hook; later traced runs would litter it).
    try:
        if _trace_settings.trace:
            _trace_settings.trace_dir = os.path.join(
                BENCH_DIR, "traces", "cold")
        ours_dir = os.path.join(BENCH_DIR, "dampr-idf")
        cold, _cold_summary = run_dampr_tpu(corpus, ours_dir)
        log("dampr_tpu cold: {:.2f}s".format(cold))
        # warm steady-state: best of two runs (this box time-shares one
        # core with unrelated tenants; a single sample is noise-prone),
        # with the wall-time split (device kernels / transfers / native
        # codec) taken from the winning run.  Epoch/delta snapshots (not
        # reset()) keep the accounting run-scoped: another in-flight
        # run's counters are never clobbered by this bench.
        from dampr_tpu.ops import devtime

        tune_section = None
        if _trace_settings.autotune_enabled():
            # Closed-loop bench tuning (settings.autotune, docs/tuning.md):
            # the warm trials become an in-process autotune session — each
            # trial re-measures under one model/playbook-suggested knob
            # vector, the winner must be byte-identical (output-dir
            # digest), and its vector persists to tuned.json so the next
            # fit sees a measured value for every explored knob.
            from dampr_tpu.obs import autotune as _autotune

            def _measure():
                epoch = devtime.epoch()
                t, summary = run_dampr_tpu(corpus, ours_dir)
                return t, (t, devtime.delta(epoch), summary)

            best, tune_report = _autotune.tune_settings_session(
                _measure, "bench-tfidf",
                digest_of=lambda _res: _autotune.dir_digest(ours_dir),
                out=log)
            tune_section = tune_report["autotune"]
            log("autotune: {:.2f}x over the baseline config (winner "
                "trial {} {}, byte_identical={})".format(
                    tune_section["improvement"],
                    tune_section["winner"]["trial"],
                    tune_section["winner"]["knobs"] or "baseline",
                    tune_section["byte_identical"]))
        else:
            best = None
        for trial in (() if best is not None else range(2)):
            if _trace_settings.trace:
                _trace_settings.trace_dir = os.path.join(
                    BENCH_DIR, "traces", "trial-{}".format(trial))
            epoch = devtime.epoch()
            t, summary = run_dampr_tpu(corpus, ours_dir)
            split = devtime.delta(epoch)
            tio = summary.get("io", {})
            trial_line = ("trial {}: {:.2f}s  spill {:.1f} MB  "
                          "merge-gens {}  io w {:.0f}/r {:.0f} MB/s  "
                          "io_wait {:.1%}".format(
                              trial, t,
                              summary.get("store", {}).get("spilled_bytes",
                                                           0) / 1e6,
                              summary.get("store", {}).get("merge_gens", 0),
                              tio.get("spill_write_mbps", 0.0),
                              tio.get("spill_read_mbps", 0.0),
                              tio.get("io_wait_fraction", 0.0)))
            sampler = summary.get("metrics", {}).get("sampler", {})
            if sampler.get("samples"):
                trial_line += "  sampler {}x @{}ms ovh {:.2%}".format(
                    sampler["samples"], sampler.get("interval_ms", 0),
                    sampler.get("overhead", 0.0))
            if summary.get("trace_file"):
                trial_line += "  trace {}".format(summary["trace_file"])
            log(trial_line)
            if best is None or t < best[0]:
                best = (t, split, summary)
    finally:
        _trace_settings.trace_dir = old_trace_dir
    secs, split, summary = best
    log("dampr_tpu warm: {:.2f}s = {:.1f} MB/s".format(secs, size_mb / secs))
    # Non-overlapped codec seconds: the codec time still on the critical
    # path.  With the overlap executor off every codec second blocks the
    # job thread that could otherwise fold (serial interleave), so it is
    # the whole codec bucket; with overlap on it shrinks to the measured
    # wall-clock union of intervals where EVERY live map slot was blocked
    # on its codec — codec time no fold anywhere could cover (devtime
    # "codec_wait").
    from dampr_tpu import settings as _settings

    overlapped = _settings.overlap_windows > 0
    codec_nonov = split["codec_wait"] if overlapped else split["codec"]
    log("wall split: device {:.2f}s, transfer {:.2f}s, codec {:.2f}s "
        "({} -> {:.2f}s non-overlapped)".format(
            split["device"], split["transfer"], split["codec"],
            "overlapped" if overlapped else "serial", codec_nonov))

    n = check_result(ours_dir, counter, total)
    log("verified {} idf entries match baseline exactly".format(n))

    value = size_mb / secs
    # Learned-cost-model decision trace (plan/model.py): what the model
    # predicted for this plan and where its choices came from — the
    # perf gate (tools/check_bench.py --trend) warns when the measured
    # number falls far below the model's own prediction.
    cost_sec = (summary.get("plan") or {}).get("cost") or {}
    predicted = cost_sec.get("predicted") or {}
    record = {
        "metric": "tfidf_docfreq_throughput",
        "value": round(value, 2),
        "unit": "MB/s",
        "vs_baseline": round(value / (size_mb / base_secs), 2),
        # Thread-seconds per wall second for the winning warm run (see
        # ops/devtime.py): device kernel dispatch+sync, host<->device
        # transfers, the native C codec.  Utilization-style — concurrent
        # pool workers sum, so a value can exceed 1.0 on multi-core
        # hosts (2.0 = two cores' worth).  The single-chip claim made
        # explicit: everything else is generic host Python/numpy.
        "device_fraction": round(split["device"] / secs, 4),
        "transfer_fraction": round(split["transfer"] / secs, 4),
        "codec_fraction": round(split["codec"] / secs, 4),
        # Device lowering (dampr_tpu.plan.lower, winning warm run): how
        # many plan stages compiled to jitted device programs and the
        # feed/drain bytes the host moved for them — the evidence behind
        # device_fraction (0 stages + fraction ~0 = the host-codec leg).
        "lower": _settings.lower_enabled(),
        "device_stages": summary.get("device", {}).get("device_stages"),
        "h2d_bytes": summary.get("device", {}).get("h2d_bytes"),
        "d2h_bytes": summary.get("device", {}).get("d2h_bytes"),
        # Cross-stage device handoff (docs/plan.md "Cross-stage device
        # fusion", winning warm run): edges the plan kept HBM-resident,
        # device bytes registered without a host round-trip, and the
        # drain bytes the table-mode programs never fetched (the CI
        # trace-smoke gate reads these).
        "handoff_edges": summary.get("device", {}).get("handoff_edges"),
        "handoff_bytes": summary.get("device", {}).get("handoff_bytes"),
        "d2h_avoided_bytes": summary.get("device", {}).get(
            "d2h_avoided_bytes"),
        "handoff_degrades": summary.get("device", {}).get(
            "handoff_degrades"),
        # Codec-attributable NON-overlapped fraction of the wall: codec
        # seconds the fold actually waited on (the full codec bucket when
        # the overlap executor is off).  This is the number the overlap
        # work moves; codec_fraction above stays the total thread-seconds
        # the codec burned, overlapped or not.
        "codec_nonoverlapped_fraction": round(codec_nonov / secs, 4),
        "overlap_windows": _settings.overlap_windows,
        # Run-scoped observability (winning warm run): spill/merge volume
        # from the per-run summary, plus artifact locations when tracing
        # was on (DAMPR_TPU_TRACE=1) — stats.json carries per-stage
        # records/bytes/spill and the trace loads in Perfetto.
        "spilled_mb": round(summary.get("store", {}).get(
            "spilled_bytes", 0) / 1e6, 1),
        "merge_generations": summary.get("store", {}).get("merge_gens", 0),
        # Async spill I/O (dampr_tpu.io, winning warm run): post-codec
        # disk bandwidth each way and the fold-side stall fraction —
        # what the background writer pool / prefetching reader move.
        "spill_write_mbps": summary.get("io", {}).get("spill_write_mbps"),
        "spill_read_mbps": summary.get("io", {}).get("spill_read_mbps"),
        "io_wait_fraction": summary.get("io", {}).get("io_wait_fraction"),
        # Live metrics plane: sampler self-overhead for the winning run
        # (None when the plane was off — the default untraced path).
        "sampler_overhead": summary.get("metrics", {}).get(
            "sampler", {}).get("overhead"),
        # Logical plan optimizer (dampr_tpu.plan, winning warm run):
        # constructed vs executed stage counts and the rules that fired —
        # the fused-vs-unfused shape baselines capture (identical counts
        # under DAMPR_TPU_OPTIMIZE=0).
        "optimize": _settings.optimize,
        "plan_stages_before": summary.get("plan", {}).get("stages_before"),
        "plan_stages_after": summary.get("plan", {}).get("stages_after"),
        "trace_file": summary.get("trace_file"),
        "stats_file": summary.get("stats_file"),
        "cost_source": cost_sec.get("source"),
        "cost_choices_applied": sum(
            1 for c in cost_sec.get("choices") or () if c.get("applied")),
        "model_predicted_value": predicted.get("mbps"),
        "n_partitions": summary.get("n_partitions"),
    }
    if tune_section is not None:
        record["autotune"] = tune_section
    print(json.dumps(record))


if __name__ == "__main__":
    main()
